"""Worker-side multi-hive failover (hive.py endpoint pinning) against
the two-endpoint FakeHive mode — the quick-tier half of ISSUE 7 (the
real-server half lives in tests/test_hive_replication.py and the chaos
scenarios).

Covers: failover off a severed (dead) primary and off a 409 not-primary
refusal, result delivery landing on the surviving hive, epoch
learn-and-echo, the /healthz hive block and failover metrics, the
sdaas_uris endpoint parsing, and the shared module-level client cache.
"""

import asyncio

import pytest

from chiaswarm_tpu import hive as hive_mod
from chiaswarm_tpu import telemetry
from chiaswarm_tpu import worker as worker_mod
from chiaswarm_tpu.chips.allocator import SliceAllocator
from chiaswarm_tpu.hive import HiveClient, hive_endpoints, shared_client
from chiaswarm_tpu.settings import Settings
from chiaswarm_tpu.worker import Worker

from .fake_hive import FakeHive, FakeHivePair


@pytest.fixture(autouse=True)
def fast_poll(monkeypatch):
    monkeypatch.setattr(worker_mod, "POLL_SECONDS", 0.05)
    monkeypatch.setattr(worker_mod, "ERROR_BACKOFF_SECONDS", 0.2)


def echo_job(job_id: str) -> dict:
    return {"id": job_id, "workflow": "echo", "model_name": "none",
            "prompt": job_id}


def _settings(**overrides) -> Settings:
    base = dict(sdaas_token="failover-token", worker_name="failover-worker",
                metrics_port=0, hive_failover_errors=2)
    base.update(overrides)
    return Settings(**base)


# --- endpoint parsing -------------------------------------------------------


def test_hive_endpoints_multi_and_fallback():
    s = Settings(sdaas_uri="http://one:9511")
    assert hive_endpoints(s) == ["http://one:9511/api"]
    s = Settings(sdaas_uri="http://one:9511",
                 sdaas_uris="http://a:1/, http://b:2;http://c:3/api")
    assert hive_endpoints(s) == [
        "http://a:1/api", "http://b:2/api", "http://c:3/api"]


def test_settings_env_overrides_for_failover_knobs(monkeypatch):
    from chiaswarm_tpu.settings import load_settings

    monkeypatch.setenv("CHIASWARM_HIVE_URIS", "http://p:1,http://s:2")
    monkeypatch.setenv("CHIASWARM_HIVE_FAILOVER_GRACE_S", "3.5")
    monkeypatch.setenv("CHIASWARM_HIVE_STANDBY_OF", "http://p:1")
    monkeypatch.setenv("CHIASWARM_HIVE_REPLICATION_POLL_S", "0.25")
    monkeypatch.setenv("CHIASWARM_HIVE_FAILOVER_ERRORS", "5")
    s = load_settings()
    assert s.sdaas_uris == "http://p:1,http://s:2"
    assert s.hive_failover_grace_s == 3.5
    assert s.hive_standby_of == "http://p:1"
    assert s.hive_replication_poll_s == 0.25
    assert s.hive_failover_errors == 5


# --- client-level failover --------------------------------------------------


def test_client_fails_over_on_not_primary_409(sdaas_root):
    async def scenario():
        pair = await FakeHivePair().start()
        # inverted roles: the FIRST endpoint refuses as not-primary (a
        # deposed/standby hive), the second serves
        pair.primary.not_primary = "deposed"
        pair.standby.not_primary = None
        pair.standby.add_job(echo_job("fo-409"))
        client = HiveClient(_settings(), pair.uris)
        try:
            jobs = await client.ask_for_work({"chips": 1})
            assert [j["id"] for j in jobs] == ["fo-409"]
            assert client.failovers >= 1
            assert client.hive_uri == pair.standby.uri
        finally:
            await client.close()
            await pair.stop()

    asyncio.run(scenario())


def test_client_fails_over_after_consecutive_transport_errors(sdaas_root):
    async def scenario():
        pair = await FakeHivePair().start()
        pair.fail_over()  # primary severed, standby promoted
        client = HiveClient(_settings(hive_failover_errors=2), pair.uris)
        try:
            # two polls die on the severed primary, the pin advances
            for _ in range(2):
                with pytest.raises(Exception):
                    await client.ask_for_work({"chips": 1})
            assert client.hive_uri == pair.standby.uri
            pair.standby.add_job(echo_job("fo-sever"))
            jobs = await client.ask_for_work({"chips": 1})
            assert [j["id"] for j in jobs] == ["fo-sever"]
        finally:
            await client.close()
            await pair.stop()

    asyncio.run(scenario())


def test_submit_result_lands_on_survivor_and_echoes_epoch(sdaas_root):
    async def scenario():
        pair = await FakeHivePair().start()
        pair.primary.not_primary = "deposed"
        pair.standby.not_primary = None
        pair.standby.epoch = 2
        client = HiveClient(_settings(), pair.uris)
        try:
            ack = await client.submit_result(
                {"id": "fo-res", "artifacts": {}})
            assert ack == {"status": "ok"}
            assert [r["id"] for r in pair.standby.results] == ["fo-res"]
            assert pair.primary.results == []
            # the survivor's epoch was learned and is echoed from now on
            assert client.epoch == 2
            await client.submit_result({"id": "fo-res2", "artifacts": {}})
            assert "2" in pair.standby.seen_epochs
        finally:
            await client.close()
            await pair.stop()

    asyncio.run(scenario())


# --- whole-worker failover (quick tier, no real server) ---------------------


def test_worker_fails_over_and_reports_it(sdaas_root):
    async def scenario():
        pair = await FakeHivePair().start()
        pair.fail_over()  # the primary is dead from the start
        pair.standby.add_job(echo_job("fo-worker"))
        failovers = telemetry.REGISTRY.get("swarm_hive_failover_total")
        before = failovers.value()
        w = Worker(settings=_settings(),
                   allocator=SliceAllocator(chips_per_job=0),
                   hive_uri=pair.uris)
        runner = asyncio.create_task(w.run())
        try:
            results = await pair.standby.wait_for_results(1, timeout=60.0)
            assert results[0]["id"] == "fo-worker"
            health = w._health()
            assert health["hive"]["active_endpoint"] == pair.standby.uri
            assert health["hive"]["endpoints"] == pair.uris
            assert health["hive"]["failovers"] >= 1
            assert failovers.value() > before
            # the per-endpoint error counter saw the dead primary
            errors = telemetry.REGISTRY.get(
                "swarm_hive_endpoint_errors_total")
            assert errors.value(uri=pair.primary.uri) > 0
        finally:
            w.stop()
            await asyncio.wait_for(runner, 10)
            await pair.stop()

    asyncio.run(scenario())


def test_epoch_persists_across_client_restarts(sdaas_root):
    """The fencing epoch survives a worker restart: outbox redelivery
    from a fresh process must still refuse to hand its envelope to a
    revived deposed primary (in-memory-only epoch would reopen the
    double-settle hole)."""

    async def scenario():
        hive = await FakeHive().start()
        hive.epoch = 3
        client = HiveClient(_settings(), [hive.uri])
        try:
            await client.ask_for_work({"chips": 1})
            assert client.epoch == 3
        finally:
            await client.close()
            await hive.stop()
        # 'restart': a brand-new client in the same $SDAAS_ROOT starts
        # at the persisted epoch and echoes it immediately
        reborn = HiveClient(_settings(), ["http://unused:1/api"])
        assert reborn.epoch == 3
        assert reborn._headers()["X-Hive-Epoch"] == "3"
        await reborn.close()

    asyncio.run(scenario())


# --- shared module-level clients -------------------------------------------


def test_module_helpers_reuse_one_client(sdaas_root):
    settings = _settings()
    a = shared_client(settings, "http://h:1/api")
    b = shared_client(settings, "http://h:1/api")
    assert a is b
    c = shared_client(settings, "http://other:1/api")
    assert c is not a


def test_module_get_models_survives_sequential_event_loops(sdaas_root):
    """The shared client must work across asyncio.run calls (the
    reference-signature helpers are used from short-lived CLIs like
    initialize.py): the session re-opens per loop instead of dying with
    the first one."""

    async def fetch(uri):
        return await hive_mod.get_models(uri)

    async def run_once():
        hive = await FakeHive().start()
        try:
            models = await fetch(hive.uri)
            assert any("stable-diffusion" in m["id"] for m in models)
        finally:
            await hive.stop()

    asyncio.run(run_once())
    asyncio.run(run_once())  # second loop: the cached client must adapt
    asyncio.run(hive_mod.close_shared_clients())
