"""Video pipeline tests: temporal UNet, txt2vid/img2vid jobs, vid2vid batch,
and the cv2/PIL export helpers."""

import base64
import os

import numpy as np
import pytest
from PIL import Image

import jax
import jax.numpy as jnp

from chiaswarm_tpu import registry
from chiaswarm_tpu.models import configs as cfgs
from chiaswarm_tpu.models.video_unet import TemporalTransformer, VideoUNet, VideoUNetConfig
from chiaswarm_tpu.pipelines import video as video_pipelines
from chiaswarm_tpu.toolbox import video_helpers


@pytest.fixture(autouse=True)
def clean_registry():
    registry.clear_cache()
    yield
    registry.clear_cache()


def test_temporal_transformer_zero_init_is_identity():
    frames = 4
    module = TemporalTransformer(32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((frames, 8, 8, 32)),
                    jnp.float32)
    params = module.init(jax.random.key(0), x, frames)["params"]
    out = module.apply({"params": params}, x, frames)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_temporal_transformer_clips_stay_independent():
    """Runtime frame count < configured max must not mix clips (the CFG
    uncond/cond halves ride as separate clips in one batch)."""
    frames = 4
    rng = np.random.default_rng(0)
    module = TemporalTransformer(32)
    clip_a = jnp.asarray(rng.standard_normal((frames, 8, 8, 32)), jnp.float32)
    clip_b = jnp.asarray(rng.standard_normal((frames, 8, 8, 32)), jnp.float32)
    params = module.init(jax.random.key(0), clip_a, frames)["params"]
    # non-zero proj_out so temporal attention actually flows
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(rng.standard_normal(x.shape) * 0.05, jnp.float32),
        params,
    )
    both = module.apply(
        {"params": params}, jnp.concatenate([clip_a, clip_b], axis=0), frames
    )
    alone = module.apply({"params": params}, clip_a, frames)
    np.testing.assert_allclose(
        np.asarray(both[:frames]), np.asarray(alone), rtol=2e-4, atol=2e-5
    )


def test_video_unet_shapes():
    cfg = VideoUNetConfig(base=cfgs.TINY_UNET, num_frames=4)
    unet = VideoUNet(cfg)
    x = jnp.zeros((4, 8, 8, 4))
    ctx = jnp.zeros((4, 77, cfg.base.cross_attention_dim))
    params = unet.init(jax.random.key(0), x, jnp.zeros((4,)), ctx)["params"]
    out = unet.apply({"params": params}, x, jnp.zeros((4,)), ctx)
    assert out.shape == (4, 8, 8, 4)


def test_video_unet_runtime_frames_below_config():
    """An 8-frame config serving a 4-frame CFG-doubled batch reshapes by the
    RUNTIME clip length, not the configured maximum."""
    cfg = VideoUNetConfig(base=cfgs.TINY_UNET, num_frames=8)
    unet = VideoUNet(cfg)
    ctx8 = jnp.zeros((8, 77, cfg.base.cross_attention_dim))
    params = unet.init(
        jax.random.key(0), jnp.zeros((8, 8, 8, 4)), jnp.zeros((8,)), ctx8
    )["params"]
    # 2 clips x 4 frames (uncond|cond) with runtime num_frames=4
    out = unet.apply(
        {"params": params}, jnp.zeros((8, 8, 8, 4)), jnp.zeros((8,)), ctx8,
        num_frames=4,
    )
    assert out.shape == (8, 8, 8, 4)


def test_txt2vid_job_produces_video_artifact():
    artifacts, config = video_pipelines.run_txt2vid(
        "cpu", "damo-vilab/text-to-video-ms-1.7b",
        prompt="a rocket", num_inference_steps=2, num_frames=4,
        height=64, width=64, test_tiny_model=True,
        pipeline_type="DiffusionPipeline",  # hive wire default gets coerced
        rng=jax.random.key(0),
    )
    assert config["frames"] == 4
    primary = artifacts["primary"]
    assert primary["content_type"] in ("video/mp4", "image/gif")
    assert len(base64.b64decode(primary["blob"])) > 100
    assert primary["thumbnail"]


def test_img2vid_job_conditions_on_image():
    start = Image.fromarray(
        (np.random.default_rng(1).random((64, 64, 3)) * 255).astype(np.uint8)
    )
    artifacts, config = video_pipelines.run_img2vid(
        "cpu", "stabilityai/stable-video-diffusion-img2vid",
        image=start, num_inference_steps=2, num_frames=4,
        test_tiny_model=True, rng=jax.random.key(0),
    )
    assert artifacts["primary"]["blob"]
    assert config["frames"] == 4

    with pytest.raises(ValueError, match="requires an input image"):
        video_pipelines.run_img2vid(
            "cpu", "svd", test_tiny_model=True, num_inference_steps=2,
            rng=jax.random.key(0),
        )


def test_export_roundtrip(tmp_path):
    frames = [
        Image.fromarray(
            (np.random.default_rng(i).random((64, 64, 3)) * 255).astype(np.uint8)
        )
        for i in range(4)
    ]
    buffer, ctype = video_helpers.export_frames(frames, "video/mp4", fps=4)
    assert buffer.getbuffer().nbytes > 0
    if ctype == "video/mp4":  # cv2 encoded: split it back
        path = tmp_path / "clip.mp4"
        path.write_bytes(buffer.getvalue())
        back, fps = video_helpers.split_video_frames(str(path))
        assert len(back) == 4
        assert back[0].size == (64, 64)

    gif, _ = video_helpers.export_frames(frames, "image/gif", fps=4)
    assert gif.getvalue()[:3] == b"GIF"


def test_vid2vid_batches_frames(tmp_path, monkeypatch):
    frames = [
        Image.fromarray(np.full((64, 64, 3), i * 40, np.uint8)) for i in range(5)
    ]
    buffer, ctype = video_helpers.export_frames(frames, "video/mp4", fps=4)
    if ctype != "video/mp4":
        pytest.skip("cv2 mp4 encoder unavailable")
    clip = tmp_path / "in.mp4"
    clip.write_bytes(buffer.getvalue())

    monkeypatch.setattr(
        video_pipelines, "download_video", lambda uri, **kw: str(clip)
    )
    # download cleanup unlinks the path; keep the fixture file
    real_unlink = os.unlink
    monkeypatch.setattr(
        video_pipelines.os, "unlink",
        lambda p: None if p == str(clip) else real_unlink(p),
    )

    artifacts, config = video_pipelines.run_vid2vid(
        "cpu", "timbrooks/instruct-pix2pix",
        video_uri="http://example.org/in.mp4",
        prompt="make it snow", num_inference_steps=2, strength=0.5,
        test_tiny_model=True, rng=jax.random.key(0),
    )
    assert config["frames"] == 5
    assert config["compute_cost"] == 512 * 512 * 2 * 5
    assert artifacts["primary"]["blob"]
