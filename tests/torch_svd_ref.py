"""Exact-key torch mirror of the diffusers Stable Video Diffusion graphs
(UNetSpatioTemporalConditionModel + AutoencoderKLTemporalDecoder), proving
the flax modules + conversion numerically (same in-repo-reference strategy
as torch_unet_ref.py / torch_cascade_ref.py).

Keys match diffusers exactly: spatio-temporal res pairs
(`spatial_res_block` / `temporal_res_block` / `time_mixer.mix_factor`),
transformer pairs (`transformer_blocks` / `temporal_transformer_blocks` /
`time_pos_embed`), SDXL-style `add_embedding` micro-conditioning, and the
temporal decoder's trailing `time_conv_out`.
"""

import torch
import torch.nn as nn
import torch.nn.functional as F

from torch_unet_ref import (
    AttentionT,
    EncoderT,
    FeedForwardT,
    ResnetT,
    TimestepEmbeddingT,
    VAEAttnT,
    timestep_embedding_t,
)


class AlphaBlenderT(nn.Module):
    def __init__(self, strategy="learned_with_images", switch=False):
        super().__init__()
        self.strategy = strategy
        self.switch = switch
        self.mix_factor = nn.Parameter(torch.Tensor([0.5]))

    def forward(self, x_spatial, x_temporal, image_only_indicator=None):
        alpha = torch.sigmoid(self.mix_factor)[0]
        if self.strategy == "learned_with_images" and image_only_indicator is not None:
            flags = image_only_indicator.bool()
            while flags.ndim < x_spatial.ndim:
                flags = flags.unsqueeze(-1)
            alpha = torch.where(flags, torch.ones_like(alpha), alpha)
        if self.switch:
            alpha = 1.0 - alpha
        return alpha * x_spatial + (1.0 - alpha) * x_temporal


class TemporalResnetT(nn.Module):
    """TemporalResnetBlock: (3,1,1) 3D convs on [B, C, F, H, W]."""

    def __init__(self, in_ch, out_ch, temb_dim=None, eps=1e-6):
        super().__init__()
        self.norm1 = nn.GroupNorm(32, in_ch, eps=eps)
        self.conv1 = nn.Conv3d(in_ch, out_ch, (3, 1, 1), padding=(1, 0, 0))
        if temb_dim:
            self.time_emb_proj = nn.Linear(temb_dim, out_ch)
        self.norm2 = nn.GroupNorm(32, out_ch, eps=eps)
        self.conv2 = nn.Conv3d(out_ch, out_ch, (3, 1, 1), padding=(1, 0, 0))
        if in_ch != out_ch:
            self.conv_shortcut = nn.Conv3d(in_ch, out_ch, 1)
        self._has_temb = bool(temb_dim)
        self._short = in_ch != out_ch

    def forward(self, x, temb=None):
        h = self.conv1(F.silu(self.norm1(x)))
        if self._has_temb and temb is not None:
            # temb [B, F, C] -> [B, C, F, 1, 1]
            h = h + self.time_emb_proj(F.silu(temb)).permute(0, 2, 1)[
                :, :, :, None, None
            ]
        h = self.conv2(F.silu(self.norm2(h)))
        if self._short:
            x = self.conv_shortcut(x)
        return x + h


class SpatioTemporalResT(nn.Module):
    def __init__(self, in_ch, out_ch, temb_dim=None, eps=1e-5,
                 temporal_eps=None, strategy="learned_with_images",
                 switch=False):
        super().__init__()
        self.spatial_res_block = ResnetT(in_ch, out_ch, temb_dim, eps=eps)
        self.temporal_res_block = TemporalResnetT(
            out_ch, out_ch, temb_dim,
            eps=temporal_eps if temporal_eps is not None else eps,
        )
        self.time_mixer = AlphaBlenderT(strategy, switch)

    def forward(self, x, temb, image_only_indicator):
        num_frames = image_only_indicator.shape[-1]
        h = self.spatial_res_block(x, temb)
        bf, c, hh, ww = h.shape
        b = bf // num_frames
        h5 = h.reshape(b, num_frames, c, hh, ww).permute(0, 2, 1, 3, 4)
        temb5 = temb.reshape(b, num_frames, -1) if temb is not None else None
        ht = self.temporal_res_block(h5, temb5)
        mixed = self.time_mixer(
            h5, ht,
            image_only_indicator[:, None, :, None, None]
            if self.time_mixer.strategy == "learned_with_images"
            else None,
        )
        return mixed.permute(0, 2, 1, 3, 4).reshape(bf, c, hh, ww)


class BasicBlockSVDT(nn.Module):
    """Spatial BasicTransformerBlock (self + cross to image tokens)."""

    def __init__(self, dim, heads, head_dim, cross_dim):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = AttentionT(dim, heads, head_dim)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = AttentionT(dim, heads, head_dim, cross_dim=cross_dim)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = FeedForwardT(dim)

    def forward(self, x, context):
        x = x + self.attn1(self.norm1(x))
        x = x + self.attn2(self.norm2(x), context)
        return x + self.ff(self.norm3(x))


class TemporalBasicBlockT(nn.Module):
    def __init__(self, dim, heads, head_dim, cross_dim):
        super().__init__()
        self.norm_in = nn.LayerNorm(dim)
        self.ff_in = FeedForwardT(dim)
        self.norm1 = nn.LayerNorm(dim)
        self.attn1 = AttentionT(dim, heads, head_dim)
        self.norm2 = nn.LayerNorm(dim)
        self.attn2 = AttentionT(dim, heads, head_dim, cross_dim=cross_dim)
        self.norm3 = nn.LayerNorm(dim)
        self.ff = FeedForwardT(dim)

    def forward(self, hidden, num_frames, encoder_hidden_states):
        bf, s, c = hidden.shape
        b = bf // num_frames
        hidden = hidden.reshape(b, num_frames, s, c).permute(0, 2, 1, 3)
        hidden = hidden.reshape(b * s, num_frames, c)
        residual = hidden
        hidden = self.ff_in(self.norm_in(hidden))
        hidden = hidden + residual  # is_res (dim == inner)
        hidden = self.attn1(self.norm1(hidden)) + hidden
        hidden = self.attn2(self.norm2(hidden), encoder_hidden_states) + hidden
        hidden = self.ff(self.norm3(hidden)) + hidden
        hidden = hidden.reshape(b, s, num_frames, c).permute(0, 2, 1, 3)
        return hidden.reshape(bf, s, c)


class TransformerSpatioTemporalT(nn.Module):
    def __init__(self, ch, heads, head_dim, layers, cross_dim):
        super().__init__()
        inner = heads * head_dim
        self.norm = nn.GroupNorm(32, ch, eps=1e-6)
        self.proj_in = nn.Linear(ch, inner)
        self.transformer_blocks = nn.ModuleList(
            [BasicBlockSVDT(inner, heads, head_dim, cross_dim)
             for _ in range(layers)]
        )
        self.temporal_transformer_blocks = nn.ModuleList(
            [TemporalBasicBlockT(inner, heads, head_dim, cross_dim)
             for _ in range(layers)]
        )
        self._ch = ch
        self.time_pos_embed = TimestepEmbeddingT4(ch, ch * 4, ch)
        self.time_mixer = AlphaBlenderT("learned_with_images")
        self.proj_out = nn.Linear(inner, ch)

    def forward(self, x, context, image_only_indicator):
        bf, c, hh, ww = x.shape
        num_frames = image_only_indicator.shape[-1]
        b = bf // num_frames

        ctx_first = context.reshape(b, num_frames, -1, context.shape[-1])[:, 0]
        time_context = ctx_first[:, None].expand(
            b, hh * ww, ctx_first.shape[1], ctx_first.shape[2]
        ).reshape(b * hh * ww, ctx_first.shape[1], ctx_first.shape[2])

        residual = x
        hidden = self.norm(x).permute(0, 2, 3, 1).reshape(bf, hh * ww, c)
        hidden = self.proj_in(hidden)

        frame_ids = torch.arange(num_frames).repeat(b)
        emb = self.time_pos_embed(timestep_embedding_t(frame_ids, c))[:, None]

        for block, tblock in zip(
            self.transformer_blocks, self.temporal_transformer_blocks
        ):
            hidden = block(hidden, context)
            mix = hidden + emb
            mix = tblock(mix, num_frames, time_context)
            s = hidden.shape[1]
            sp = hidden.reshape(b, num_frames, s, c)
            tp = mix.reshape(b, num_frames, s, c)
            hidden = self.time_mixer(
                sp, tp, image_only_indicator
            ).reshape(bf, s, c)
        hidden = self.proj_out(hidden)
        return hidden.reshape(bf, hh, ww, c).permute(0, 3, 1, 2) + residual


class TimestepEmbeddingT4(nn.Module):
    """TimestepEmbedding with out_dim != hidden dim (time_pos_embed)."""

    def __init__(self, in_dim, hidden, out_dim):
        super().__init__()
        self.linear_1 = nn.Linear(in_dim, hidden)
        self.linear_2 = nn.Linear(hidden, out_dim)

    def forward(self, x):
        return self.linear_2(F.silu(self.linear_1(x)))


class _Stage(nn.Module):
    pass


class _DownST(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2d(ch, ch, 3, stride=2, padding=1)

    def forward(self, x):
        return self.conv(x)


class _UpST(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.conv = nn.Conv2d(ch, ch, 3, padding=1)

    def forward(self, x):
        return self.conv(F.interpolate(x, scale_factor=2.0, mode="nearest"))


class UNetSpatioTemporalT(nn.Module):
    """Mirror driven by the SAME SVDUNetConfig dataclass as the flax
    module, emitting the diffusers key layout."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        blocks = cfg.block_out_channels
        temb_dim = blocks[0] * 4
        self.conv_in = nn.Conv2d(cfg.in_channels, blocks[0], 3, padding=1)
        self.time_embedding = TimestepEmbeddingT(blocks[0], temb_dim)
        self.add_embedding = TimestepEmbeddingT(
            cfg.projection_class_embeddings_input_dim, temb_dim
        )

        def attn_stage(level):
            ch = blocks[level]
            heads = cfg.num_attention_heads[level]
            return TransformerSpatioTemporalT(
                ch, heads, ch // heads, cfg.transformer_layers_per_block,
                cfg.cross_attention_dim,
            )

        self.down_blocks = nn.ModuleList()
        ch = blocks[0]
        for i, out_ch in enumerate(blocks):
            stage = _Stage()
            stage.resnets = nn.ModuleList()
            if cfg.attention[i]:
                stage.attentions = nn.ModuleList()
            for j in range(cfg.layers_per_block):
                stage.resnets.append(
                    SpatioTemporalResT(ch if j == 0 else out_ch, out_ch, temb_dim)
                )
                if cfg.attention[i]:
                    stage.attentions.append(attn_stage(i))
            if i != len(blocks) - 1:
                stage.downsamplers = nn.ModuleList([_DownST(out_ch)])
            self.down_blocks.append(stage)
            ch = out_ch

        mid = _Stage()
        mid.resnets = nn.ModuleList([
            SpatioTemporalResT(blocks[-1], blocks[-1], temb_dim),
            SpatioTemporalResT(blocks[-1], blocks[-1], temb_dim),
        ])
        mid.attentions = nn.ModuleList([attn_stage(len(blocks) - 1)])
        self.mid_block = mid

        skip_chs = [blocks[0]]
        for i, out_ch in enumerate(blocks):
            skip_chs += [out_ch] * cfg.layers_per_block
            if i != len(blocks) - 1:
                skip_chs.append(out_ch)
        self.up_blocks = nn.ModuleList()
        ch = blocks[-1]
        for bi, out_ch in enumerate(reversed(blocks)):
            rev = len(blocks) - 1 - bi
            stage = _Stage()
            stage.resnets = nn.ModuleList()
            if cfg.attention[rev]:
                stage.attentions = nn.ModuleList()
            for j in range(cfg.layers_per_block + 1):
                skip = skip_chs.pop()
                stage.resnets.append(
                    SpatioTemporalResT(ch + skip, out_ch, temb_dim)
                )
                if cfg.attention[rev]:
                    stage.attentions.append(attn_stage(rev))
                ch = out_ch
            if bi != len(blocks) - 1:
                stage.upsamplers = nn.ModuleList([_UpST(out_ch)])
            self.up_blocks.append(stage)

        self.conv_norm_out = nn.GroupNorm(32, blocks[0], eps=1e-5)
        self.conv_out = nn.Conv2d(blocks[0], cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, encoder_hidden_states, added_time_ids):
        cfg = self.cfg
        b, num_frames = sample.shape[0], sample.shape[1]
        temb = self.time_embedding(
            timestep_embedding_t(timesteps, cfg.block_out_channels[0])
        )
        tid = timestep_embedding_t(
            added_time_ids.flatten(), cfg.addition_time_embed_dim
        ).reshape(b, -1)
        temb = temb + self.add_embedding(tid)

        x = sample.flatten(0, 1)
        temb = temb.repeat_interleave(num_frames, dim=0)
        context = encoder_hidden_states.repeat_interleave(num_frames, dim=0)
        indicator = torch.zeros(b, num_frames)

        x = self.conv_in(x)
        skips = [x]
        for stage in self.down_blocks:
            for j, resnet in enumerate(stage.resnets):
                x = resnet(x, temb, indicator)
                if hasattr(stage, "attentions"):
                    x = stage.attentions[j](x, context, indicator)
                skips.append(x)
            if hasattr(stage, "downsamplers"):
                x = stage.downsamplers[0](x)
                skips.append(x)

        x = self.mid_block.resnets[0](x, temb, indicator)
        x = self.mid_block.attentions[0](x, context, indicator)
        x = self.mid_block.resnets[1](x, temb, indicator)

        for stage in self.up_blocks:
            for j, resnet in enumerate(stage.resnets):
                x = torch.cat([x, skips.pop()], dim=1)
                x = resnet(x, temb, indicator)
                if hasattr(stage, "attentions"):
                    x = stage.attentions[j](x, context, indicator)
            if hasattr(stage, "upsamplers"):
                x = stage.upsamplers[0](x)

        x = self.conv_out(F.silu(self.conv_norm_out(x)))
        return x.reshape(b, num_frames, *x.shape[1:])


class _MidTD(nn.Module):
    def __init__(self, ch, layers):
        super().__init__()
        self.resnets = nn.ModuleList([
            SpatioTemporalResT(ch, ch, None, eps=1e-6, temporal_eps=1e-5,
                               strategy="learned", switch=True)
            for _ in range(layers)
        ])
        self.attentions = nn.ModuleList([VAEAttnT(ch)])

    def forward(self, x, indicator):
        x = self.resnets[0](x, None, indicator)
        for resnet in self.resnets[1:]:
            x = self.attentions[0](x)
            x = resnet(x, None, indicator)
        return x


class _UpTD(nn.Module):
    def __init__(self, in_ch, out_ch, layers, add_up):
        super().__init__()
        self.resnets = nn.ModuleList([
            SpatioTemporalResT(in_ch if i == 0 else out_ch, out_ch, None,
                               eps=1e-6, temporal_eps=1e-5,
                               strategy="learned", switch=True)
            for i in range(layers)
        ])
        if add_up:
            self.upsamplers = nn.ModuleList([_UpST(out_ch)])

    def forward(self, x, indicator):
        for r in self.resnets:
            x = r(x, None, indicator)
        if hasattr(self, "upsamplers"):
            x = self.upsamplers[0](x)
        return x


class TemporalDecoderT(nn.Module):
    def __init__(self, cfg):
        super().__init__()
        blocks = cfg.block_out_channels
        rev = list(reversed(blocks))
        self.conv_in = nn.Conv2d(cfg.latent_channels, rev[0], 3, padding=1)
        self.mid_block = _MidTD(rev[0], 2)
        self.up_blocks = nn.ModuleList()
        ch = rev[0]
        for i, out_ch in enumerate(rev):
            self.up_blocks.append(
                _UpTD(ch, out_ch, cfg.layers_per_block + 1,
                      add_up=i != len(rev) - 1)
            )
            ch = out_ch
        self.conv_norm_out = nn.GroupNorm(32, blocks[0], eps=1e-6)
        self.conv_out = nn.Conv2d(blocks[0], cfg.in_channels, 3, padding=1)
        self.time_conv_out = nn.Conv3d(
            cfg.in_channels, cfg.in_channels, (3, 1, 1), padding=(1, 0, 0)
        )

    def forward(self, z, num_frames):
        indicator = torch.zeros(z.shape[0] // num_frames, num_frames)
        x = self.conv_in(z)
        x = self.mid_block(x, indicator)
        for b in self.up_blocks:
            x = b(x, indicator)
        x = self.conv_out(F.silu(self.conv_norm_out(x)))
        bf, c, hh, ww = x.shape
        x = x.reshape(bf // num_frames, num_frames, c, hh, ww).permute(
            0, 2, 1, 3, 4
        )
        x = self.time_conv_out(x)
        return x.permute(0, 2, 1, 3, 4).reshape(bf, c, hh, ww)


class AutoencoderKLTemporalDecoderT(nn.Module):
    def __init__(self, cfg):
        super().__init__()
        self.encoder = EncoderT(cfg.encoder_config())
        self.decoder = TemporalDecoderT(cfg)
        self.quant_conv = nn.Conv2d(
            2 * cfg.latent_channels, 2 * cfg.latent_channels, 1
        )

    def encode_mode(self, pixels):
        moments = self.quant_conv(self.encoder(pixels))
        mean, _ = moments.chunk(2, dim=1)
        return mean

    def decode_raw(self, latents, num_frames):
        return self.decoder(latents, num_frames)
