"""swarmlint (ISSUE 15): per-rule fixtures, the suppression/baseline
workflow, and the real-tree gate.

Each rule gets a positive case (it demonstrably fires on a minimal
fixture tree mirroring the real layout), a suppressed case (the
``# swarmlint: disable=SWxxx`` escape hatch works), and the negative
shape the rule must NOT flag (the sanctioned idiom). The baseline
mechanism is exercised on fixtures, then pinned against the real tree:
zero non-baselined findings, zero stale entries, and a baseline that
only ever shrinks.
"""

import json
import subprocess
import sys
from pathlib import Path
from textwrap import dedent

import pytest

from chiaswarm_tpu.lint import RULES, Baseline, run_lint
from chiaswarm_tpu.lint.core import DEFAULT_BASELINE

REPO_ROOT = Path(__file__).resolve().parents[1]

# the grandfathered-debt ceiling: entries may be DELETED (fixing a
# finding forces it — stale entries fail the runner), never added.
# If this assertion fires because the count went UP, the new finding
# must be fixed or explicitly suppressed with a reason, not baselined.
BASELINE_CEILING = 12


def lint(tmp_path, files, rules=(), baseline=None):
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(dedent(text))
    selected = {c: RULES[c] for c in rules} if rules else None
    return run_lint(tmp_path, baseline=baseline, rules=selected)


def codes(result):
    return [f.rule for f in result.findings]


# --- SW001: jax purity -----------------------------------------------------


def test_sw001_fires_on_transitive_module_level_jax(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/hive_server/svc.py": """\
            from ..util import helper
        """,
        "chiaswarm_tpu/util.py": """\
            import jax

            def helper():
                return jax
        """,
    }, rules=("SW001",))
    assert codes(res) == ["SW001"]
    f = res.findings[0]
    assert f.path == "chiaswarm_tpu/hive_server/svc.py"
    assert "chiaswarm_tpu.util" in f.message and "jax" in f.message


def test_sw001_lazy_import_is_the_sanctioned_escape(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/hive_server/svc.py": """\
            from ..util import helper
        """,
        "chiaswarm_tpu/util.py": """\
            def helper():
                import jax  # function-local: worker-side call path only
                return jax
        """,
    }, rules=("SW001",))
    assert codes(res) == []


def test_sw001_type_checking_imports_dont_count(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/hive_server/svc.py": """\
            from typing import TYPE_CHECKING

            if TYPE_CHECKING:
                import jax
        """,
    }, rules=("SW001",))
    assert codes(res) == []


def test_sw001_suppressed(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/telemetry.py": """\
            import jax  # swarmlint: disable=SW001 -- fixture
        """,
    }, rules=("SW001",))
    assert codes(res) == []
    assert res.suppressed_count == 1


def test_sw001_direct_import_anchors_at_its_own_line(tmp_path):
    """A direct violation must report (and suppress) at the import
    statement itself, not at line 1."""
    body = """\
        import logging

        import jax{suffix}

        log = logging.getLogger(__name__)
    """
    res = lint(tmp_path, {
        "chiaswarm_tpu/telemetry.py": body.format(suffix=""),
    }, rules=("SW001",))
    assert [(f.rule, f.line) for f in res.findings] == [("SW001", 3)]
    assert res.findings[0].anchor == "import jax"
    suppressed = lint(tmp_path, {
        "chiaswarm_tpu/telemetry.py": body.format(
            suffix="  # swarmlint: disable=SW001 -- fixture"),
    }, rules=("SW001",))
    assert codes(suppressed) == []
    assert suppressed.suppressed_count == 1


# --- SW002: blocking calls in coroutines -----------------------------------


def test_sw002_fires_on_blocking_calls(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/w.py": """\
            import json
            import time

            async def poll():
                time.sleep(1)
                data = json.load(open("f.json"))
                text = path.read_text()
                return data, text
        """,
    }, rules=("SW002",))
    assert codes(res) == ["SW002"] * 4  # sleep, load, open, read_text


def test_sw002_nested_def_and_asyncio_sleep_are_clean(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/w.py": """\
            import asyncio
            import time

            async def capture(seconds):
                def run():
                    time.sleep(seconds)  # off-loop via the executor
                await asyncio.get_running_loop().run_in_executor(None, run)
                await asyncio.sleep(0.1)
        """,
    }, rules=("SW002",))
    assert codes(res) == []


def test_sw002_suppressed(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/w.py": """\
            import time

            async def f():
                time.sleep(0)  # swarmlint: disable=SW002 -- fixture
        """,
    }, rules=("SW002",))
    assert codes(res) == []
    assert res.suppressed_count == 1


# --- SW003: hive clock discipline ------------------------------------------


def test_sw003_fires_in_hive_server_only(tmp_path):
    files = {
        "chiaswarm_tpu/hive_server/q.py": """\
            import time

            def now():
                return time.time(), time.monotonic()
        """,
        # outside hive_server/ the rule does not apply
        "chiaswarm_tpu/worker_side.py": """\
            import time

            def now():
                return time.time()
        """,
        # clock.py is the one sanctioned home of the raw calls
        "chiaswarm_tpu/hive_server/clock.py": """\
            import time

            MONO = time.monotonic
        """,
    }
    res = lint(tmp_path, files, rules=("SW003",))
    assert codes(res) == ["SW003", "SW003"]
    assert {f.path for f in res.findings} == {
        "chiaswarm_tpu/hive_server/q.py"}


def test_sw003_suppressed_with_reason(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/hive_server/q.py": """\
            import time

            NOW = time.time()  # swarmlint: disable=SW003 -- fixture
        """,
    }, rules=("SW003",))
    assert codes(res) == []
    assert res.suppressed_count == 1


# --- SW004: Settings-knob drift --------------------------------------------

_SETTINGS_FIXTURE = """\
    import dataclasses

    @dataclasses.dataclass
    class Settings:
        documented: int = 1
        missing_env: int = 2
        missing_readme: int = 3
        missing_test: int = 4

    _ENV_OVERRIDES = {
        "CHIASWARM_DOCUMENTED": "documented",
        "CHIASWARM_MISSING_README": "missing_readme",
        "CHIASWARM_MISSING_TEST": "missing_test",
        "CHIASWARM_GONE": "removed_field",
    }
"""


def test_sw004_reports_every_drift_leg(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/settings.py": _SETTINGS_FIXTURE,
        "README.md": "| `documented` | `CHIASWARM_DOCUMENTED` |\n"
                     "| `missing_test` | `CHIASWARM_MISSING_TEST` |\n"
                     "`missing_env` here too\n",
        "tests/test_settings.py":
            "documented missing_env missing_readme\n",
    }, rules=("SW004",))
    messages = " | ".join(f.message for f in res.findings)
    assert codes(res) == ["SW004"] * 4
    assert "missing_env has no env override" in messages
    assert "missing_readme has no README" in messages
    assert "missing_test is never referenced" in messages
    assert "nonexistent Settings.removed_field" in messages


def test_sw004_clean_when_catalogued(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/settings.py": """\
            import dataclasses

            @dataclasses.dataclass
            class Settings:
                knob: int = 1

            _ENV_OVERRIDES = {"CHIASWARM_KNOB": "knob"}
        """,
        "README.md": "| `knob` | `CHIASWARM_KNOB` | `1` | a knob |\n",
        "tests/test_settings.py": "assert s.knob == 1\n",
    }, rules=("SW004",))
    assert codes(res) == []


# --- SW005: metric-catalog drift -------------------------------------------


def test_sw005_missing_metric_and_label_mismatch(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/m.py": """\
            from . import telemetry

            _A = telemetry.counter("swarm_undocumented_total")
            _B = telemetry.gauge(
                "swarm_labeled_thing", "help", ("tenant", "stage"))
        """,
        "README.md":
            "| `swarm_labeled_thing` | gauge | `tenant` | partial row |\n",
    }, rules=("SW005",))
    messages = " | ".join(f.message for f in res.findings)
    assert codes(res) == ["SW005", "SW005"]
    assert "swarm_undocumented_total is registered but missing" in messages
    assert "label `stage` is not in its README" in messages


def test_sw005_suffix_shorthand_and_module_consts_resolve(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/m.py": """\
            from . import telemetry

            NAME = "swarm_flow_started_total"
            _A = telemetry.counter(NAME)
            _B = telemetry.counter("swarm_flow_finished_total")
        """,
        "README.md": "| `swarm_flow_started_total` / `_finished_total` "
                     "| counter | — | lifecycle flow |\n",
    }, rules=("SW005",))
    assert codes(res) == []


# --- SW006: WAL-event exhaustiveness ---------------------------------------

_JOURNAL_SHELL = """\
    def ev_good(record):
        return {{"ev": "good", "id": record.job_id}}

    def ev_bad(record):
        return {{"ev": "bad", "id": record.job_id}}

    def snapshot_events(queue, leases):
        return [{snapshot}]

    def apply_events(events, queue, leases):
        for event in events:
            ev = event.get("ev")
            if ev == "good":
                pass
            {extra_branch}
"""


def test_sw006_missing_replay_and_compaction(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/hive_server/journal.py": _JOURNAL_SHELL.format(
            snapshot="ev_good(None)", extra_branch=""),
        "chiaswarm_tpu/hive_server/replication.py":
            "from .journal import apply_events\n",
    }, rules=("SW006",))
    messages = " | ".join(f.message for f in res.findings)
    assert codes(res) == ["SW006", "SW006"]
    assert "'bad' (ev_bad) has no replay branch" in messages
    assert "'bad' (ev_bad) is never emitted by snapshot_events" in messages


def test_sw006_clean_and_replication_contract(tmp_path):
    files = {
        "chiaswarm_tpu/hive_server/journal.py": _JOURNAL_SHELL.format(
            snapshot="ev_good(None), ev_bad(None)",
            extra_branch="elif ev == \"bad\":\n                pass"),
        "chiaswarm_tpu/hive_server/replication.py":
            "from .journal import apply_events\n",
    }
    assert codes(lint(tmp_path, files, rules=("SW006",))) == []
    # a replication module that stops riding apply_events is a finding
    files["chiaswarm_tpu/hive_server/replication.py"] = "pass\n"
    res = lint(tmp_path, files, rules=("SW006",))
    assert codes(res) == ["SW006"]
    assert "replication no longer applies" in res.findings[0].message


def test_sw006_suppression_on_constructor(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/hive_server/journal.py": """\
            def ev_folded(record):  # swarmlint: disable=SW006 -- folded
                return {"ev": "folded", "id": record.job_id}

            def snapshot_events(queue, leases):
                return []

            def apply_events(events, queue, leases):
                for event in events:
                    if event.get("ev") == "folded":
                        pass
        """,
        "chiaswarm_tpu/hive_server/replication.py":
            "from .journal import apply_events\n",
    }, rules=("SW006",))
    assert codes(res) == []
    assert res.suppressed_count == 1


# --- SW007: unbounded cache dicts ------------------------------------------


def test_sw007_fires_on_unbounded_cache_shapes(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/c.py": """\
            from collections import OrderedDict

            _RESULT_CACHE = {}

            class P:
                def __init__(self):
                    self._programs = OrderedDict()
        """,
    }, rules=("SW007",))
    assert codes(res) == ["SW007", "SW007"]


def test_sw007_popitem_lru_and_cache_classes_are_bounded(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/c.py": """\
            from collections import OrderedDict

            from .embed_cache import ByteCappedLRU

            _BOUNDED_CACHE = OrderedDict()
            _CLASS_CACHE = ByteCappedLRU(64)
            _LOOKUP_TABLE_NOT_CACHE = {"static": "entries"}

            def put(k, v):
                _BOUNDED_CACHE[k] = v
                while len(_BOUNDED_CACHE) > 8:
                    _BOUNDED_CACHE.popitem(last=False)
        """,
    }, rules=("SW007",))
    assert codes(res) == []


def test_sw007_not_masked_by_suffix_named_sibling(tmp_path):
    """`_cache.popitem` must not be satisfied by `_embed_cache.popitem`
    (raw substring would match); the eviction evidence is matched on a
    word boundary."""
    res = lint(tmp_path, {
        "chiaswarm_tpu/c.py": """\
            _cache = {}
            _embed_cache = {}

            def put(k, v):
                _embed_cache[k] = v
                while len(_embed_cache) > 8:
                    _embed_cache.popitem()
        """,
    }, rules=("SW007",))
    assert [(f.rule, f.line) for f in res.findings] == [("SW007", 1)]


def test_sw007_suppressed_with_reason(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/c.py": """\
            _TINY_CACHE = {}  # swarmlint: disable=SW007 -- vocab-bounded
        """,
    }, rules=("SW007",))
    assert codes(res) == []
    assert res.suppressed_count == 1


# --- SW008: exception hygiene ----------------------------------------------


def test_sw008_bare_except_and_swallowed_cancellation(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/e.py": """\
            import asyncio

            def sync_fn():
                try:
                    work()
                except:
                    pass

            async def loop():
                try:
                    await step()
                except asyncio.CancelledError:
                    pass
                except Exception:
                    log()
        """,
    }, rules=("SW008",))
    messages = " | ".join(f.message for f in res.findings)
    assert codes(res) == ["SW008", "SW008"]
    assert "bare `except:`" in messages
    assert "swallows task cancellation" in messages


def test_sw008_reraise_and_narrow_handlers_are_clean(tmp_path):
    res = lint(tmp_path, {
        "chiaswarm_tpu/e.py": """\
            import asyncio

            async def loop():
                try:
                    await step()
                except asyncio.CancelledError:
                    cleanup()
                    raise
                except (ValueError, OSError):
                    pass
        """,
    }, rules=("SW008",))
    assert codes(res) == []


# --- suppression / baseline workflow ---------------------------------------


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    files = {
        "chiaswarm_tpu/hive_server/q.py": """\
            import time

            NOW = time.time()
        """,
    }
    first = lint(tmp_path, files, rules=("SW003",))
    assert codes(first) == ["SW003"]
    key = first.findings[0].key

    grandfathered = lint(tmp_path, files, rules=("SW003",),
                         baseline=Baseline([key]))
    assert grandfathered.findings == []
    assert [f.key for f in grandfathered.baselined] == [key]
    assert grandfathered.stale_baseline == []

    # fix the finding: the baseline entry must surface as stale debt
    files["chiaswarm_tpu/hive_server/q.py"] = "import time\n"
    fixed = lint(tmp_path, files, rules=("SW003",), baseline=Baseline([key]))
    assert fixed.findings == []
    assert fixed.stale_baseline == [key]


def test_narrowed_run_never_judges_other_rules_baseline_stale(tmp_path):
    """`--rules SW003` must not flag the SW007 baseline entries as
    stale: only rules that actually ran can produce the findings the
    staleness check compares against."""
    files = {"chiaswarm_tpu/c.py": "_ORPHAN_CACHE = {}\n"}
    key = lint(tmp_path, files, rules=("SW007",)).findings[0].key
    narrowed = lint(tmp_path, files, rules=("SW003",),
                    baseline=Baseline([key]))
    assert narrowed.findings == [] and narrowed.stale_baseline == []
    full = lint(tmp_path, {"chiaswarm_tpu/c.py": "pass\n"},
                rules=("SW007",), baseline=Baseline([key]))
    assert full.stale_baseline == [key]


def test_baseline_key_survives_line_churn(tmp_path):
    files = {
        "chiaswarm_tpu/hive_server/q.py": """\
            import time

            NOW = time.time()
        """,
    }
    key = lint(tmp_path, files, rules=("SW003",)).findings[0].key
    files["chiaswarm_tpu/hive_server/q.py"] = (
        "import time\n\n# an\n# unrelated\n# comment block\n\n"
        "NOW = time.time()\n")
    moved = lint(tmp_path, files, rules=("SW003",), baseline=Baseline([key]))
    assert moved.findings == [] and len(moved.baselined) == 1


# --- the real tree ---------------------------------------------------------


def test_real_tree_has_zero_nonbaselined_findings():
    """The acceptance gate: `python -m chiaswarm_tpu.lint` semantics,
    in-process. Every invariant rule passes over the real repository
    with no new findings and no stale baseline entries."""
    result = run_lint(REPO_ROOT, baseline=Baseline.load(DEFAULT_BASELINE))
    assert result.parse_errors == []
    assert result.findings == [], "\n".join(
        f.render() for f in result.findings)
    assert result.stale_baseline == []


def test_baseline_only_shrinks():
    """No new grandfathered findings can be added silently: the entry
    count is pinned at (or below) the ISSUE-15 debt, and every entry is
    the one debt class deliberately deferred (SW007 compiled-program
    caches on the dormant pipelines)."""
    baseline = Baseline.load(DEFAULT_BASELINE)
    assert len(baseline.keys) <= BASELINE_CEILING
    assert all(k.startswith("SW007|") for k in baseline.keys)


def test_cli_json_smoke():
    """The runner the Makefile/CI invoke: --json parses, reports clean,
    and exits 0 on the real tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.lint", "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    verdict = json.loads(proc.stdout)
    assert verdict["clean"] is True
    assert verdict["findings"] == []
    assert verdict["stale_baseline"] == []


def test_cli_rule_listing_matches_registry():
    assert set(RULES) == {f"SW00{i}" for i in range(1, 9)}
    for code, rule in RULES.items():
        assert rule.code == code and rule.title


def test_cli_exits_nonzero_on_findings(tmp_path):
    (tmp_path / "chiaswarm_tpu").mkdir()
    (tmp_path / "chiaswarm_tpu" / "bad.py").write_text(
        "import time\n\nasync def f():\n    time.sleep(1)\n")
    proc = subprocess.run(
        [sys.executable, "-m", "chiaswarm_tpu.lint", "--root", str(tmp_path),
         "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 1
    verdict = json.loads(proc.stdout)
    assert verdict["counts"] == {"SW002": 1}
