"""Stable Cascade: stage-C prior -> stage-B decoder -> pixel decode.

Covers VERDICT missing #2 (Stable Cascade family): the
StableCascadePriorPipeline / StableCascadeDecoderPipeline wire names
resolve and produce images on tiny configs, with the prior chaining into
the decoder the way reference swarm/diffusion/pipeline_steps.py:70-90 does
(decoder consumes `image_embeddings`, 10 unguided steps).
"""

import numpy as np
import pytest

import jax

from chiaswarm_tpu import registry
from chiaswarm_tpu.pipelines.cascade import (
    PRIOR_CHANNELS,
    CascadePipeline,
    CascadePriorPipeline,
    _decoder_name_for,
    _prior_name_for,
)
from chiaswarm_tpu.weights import MissingWeightsError


@pytest.fixture(scope="module")
def tiny_prior():
    return CascadePriorPipeline("test/tiny-cascade-prior")


@pytest.fixture(scope="module")
def tiny_decoder():
    return CascadePipeline("test/tiny-cascade")


def test_prior_generates_spatial_latents(tiny_prior):
    embeds = tiny_prior.generate(
        "a red fox", num_images=2, steps=2, height=64, width=64,
        rng=jax.random.key(0),
    )
    # 64px at tiny compression 8 -> 8x8 spatial latent, 16 channels
    assert embeds.shape == (2, 8, 8, PRIOR_CHANNELS)
    assert np.isfinite(np.asarray(embeds)).all()


def test_prior_deterministic(tiny_prior):
    gen = lambda: np.asarray(
        tiny_prior.generate("same", steps=2, rng=jax.random.key(3))
    )
    np.testing.assert_array_equal(gen(), gen())


def test_decoder_from_explicit_embeddings(tiny_decoder):
    embeds = np.random.default_rng(0).standard_normal(
        (1, 8, 8, PRIOR_CHANNELS)
    ).astype(np.float32)
    images, config = tiny_decoder.run(
        image_embeddings=embeds, height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert images[0].size == (64, 64)
    assert "prior_s" not in config["timings"]  # prior stage skipped


def test_decoder_runs_prior_when_prompted(tiny_decoder):
    images, config = tiny_decoder.run(
        prompt="a fox in the snow", height=64, width=64,
        num_inference_steps=2, rng=jax.random.key(0),
    )
    assert images[0].size == (64, 64)
    assert config["timings"]["prior_s"] > 0


def test_prior_typed_job_chains_into_decoder(tiny_prior):
    # the hive schedules the PRIOR as the main pipeline with a `decoder`
    # parameter (reference diffusion_func.py:151-161)
    images, config = tiny_prior.run(
        prompt="a lighthouse",
        height=64,
        width=64,
        num_inference_steps=2,
        decoder={"model_name": "stabilityai/stable-cascade",
                 "num_inference_steps": 2},
        rng=jax.random.key(1),
    )
    assert images[0].size == (64, 64)
    assert config["prior"]["steps"] == 2
    assert config["steps"] == 2  # decoder honored its own step count


def test_embeddings_condition_the_decoder(tiny_decoder):
    rng = np.random.default_rng(1)
    kw = dict(height=64, width=64, num_inference_steps=2, rng=jax.random.key(7))
    a = np.asarray(tiny_decoder.run(
        image_embeddings=rng.standard_normal(
            (1, 8, 8, PRIOR_CHANNELS)).astype(np.float32), **kw)[0][0])
    b = np.asarray(tiny_decoder.run(
        image_embeddings=rng.standard_normal(
            (1, 8, 8, PRIOR_CHANNELS)).astype(np.float32), **kw)[0][0])
    assert not np.array_equal(a, b)


def test_decoder_batch_follows_embeddings(tiny_decoder):
    embeds = np.random.default_rng(2).standard_normal(
        (3, 8, 8, PRIOR_CHANNELS)
    ).astype(np.float32)
    images, _ = tiny_decoder.run(
        image_embeddings=embeds, height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert len(images) == 3


def test_registry_wire_names():
    pipe = registry.get_pipeline(
        "test/tiny-cascade", "StableCascadeDecoderPipeline"
    )
    assert isinstance(pipe, CascadePipeline)
    prior = registry.get_pipeline(
        "test/tiny-cascade-prior", "StableCascadePriorPipeline"
    )
    assert isinstance(prior, CascadePriorPipeline)


def test_name_mapping():
    assert _prior_name_for("test/tiny-cascade") == "test/tiny-cascade-prior"
    assert _decoder_name_for("test/tiny-cascade-prior") == "test/tiny-cascade"
    assert (
        _decoder_name_for("stabilityai/stable-cascade-prior")
        == "stabilityai/stable-cascade"
    )
    assert (
        _prior_name_for("stabilityai/stable-cascade")
        == "stabilityai/stable-cascade-prior"
    )


def test_real_weights_fail_loud():
    with pytest.raises(MissingWeightsError):
        CascadePipeline("stabilityai/stable-cascade")
    with pytest.raises(MissingWeightsError):
        CascadePriorPipeline("stabilityai/stable-cascade-prior")
