"""Ring attention in the SERVING path (VERDICT weak #4 follow-through):
when a ChipSet carves out a seq mesh axis, long self-attention inside the
jitted denoise program shards over it via ring attention — and the result
matches the single-device path (ring attention is exact).
"""

import numpy as np
import pytest

import jax

from chiaswarm_tpu.chips.device import ChipSet
from chiaswarm_tpu.ops import attention as attention_ops
from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline


def test_seq_parallel_sd_matches_replicated(monkeypatch):
    # tiny canvases never reach the production 2048-token threshold; lower
    # it through the SETTINGS surface (ring_min_seq) so the 64px latent
    # self-attention (up to 1024 tokens) rings
    monkeypatch.setenv("SDAAS_RING_MIN_SEQ", "64")

    kw = dict(prompt="a fox", height=64, width=64, num_inference_steps=2,
              rng=jax.random.key(0))
    ref = np.asarray(SDPipeline("test/tiny-sd").run(**kw)[0][0])

    chipset = ChipSet(jax.devices(), seq=2)  # data=4, seq=2 on 8 devices
    sp = np.asarray(SDPipeline("test/tiny-sd", chipset=chipset).run(**kw)[0][0])

    # exact attention, fp32 online-softmax merge: allow 8-bit rounding slack
    assert ref.shape == sp.shape
    diff = np.abs(ref.astype(np.int16) - sp.astype(np.int16))
    assert diff.max() <= 2, f"max pixel diff {diff.max()}"


def test_scope_noop_without_seq_axis():
    # seq=1 mesh: scope must not reroute anything
    chipset = ChipSet(jax.devices())
    mesh = chipset.mesh()
    with attention_ops.sequence_parallel_scope(mesh):
        assert getattr(attention_ops._SEQ_SCOPE, "mesh", None) is None


def test_ring_route_skips_cross_attention(monkeypatch):
    import jax.numpy as jnp

    monkeypatch.setenv("SDAAS_RING_MIN_SEQ", "8")
    chipset = ChipSet(jax.devices(), seq=2)
    with attention_ops.sequence_parallel_scope(chipset.mesh()):
        q = jnp.zeros((1, 16, 2, 8))
        kv = jnp.zeros((1, 6, 2, 8))  # different KV length = cross
        assert attention_ops._ring_route(q, kv, kv, 0.5) is None
        # self-attention with compatible length DOES route
        assert attention_ops._ring_route(q, q, q, 0.5) is not None


def test_allocator_threads_sequence_parallelism(monkeypatch):
    # VERDICT missing #6: the production config path (settings ->
    # SliceAllocator -> ChipSet) must be able to carve a seq axis, and a
    # job served on that slice must actually route through ring attention
    from chiaswarm_tpu.chips.allocator import SliceAllocator
    from chiaswarm_tpu.parallel import ring as ring_mod

    calls = []
    orig = ring_mod.ring_shard_map

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setenv("SDAAS_RING_MIN_SEQ", "64")
    monkeypatch.setattr(ring_mod, "ring_shard_map", spy)
    alloc = SliceAllocator(jax.devices(), sequence_parallelism=2)
    assert alloc.slices[0].seq == 2
    pipe = SDPipeline("test/tiny-sd", chipset=alloc.slices[0])
    imgs, _ = pipe.run(
        prompt="x", height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert len(imgs) == 1
    assert calls, "ring attention was never routed in the serving program"


def test_settings_sequence_parallelism_env(monkeypatch, sdaas_root):
    from chiaswarm_tpu.settings import load_settings

    monkeypatch.setenv("SDAAS_SEQUENCE_PARALLELISM", "2")
    assert load_settings().sequence_parallelism == 2


def test_settings_ring_min_seq_env(monkeypatch, sdaas_root):
    from chiaswarm_tpu.settings import load_settings

    assert load_settings().ring_min_seq == 2048  # production default
    monkeypatch.setenv("SDAAS_RING_MIN_SEQ", "64")
    assert load_settings().ring_min_seq == 64


def test_production_threshold_rings_at_4096_tokens(monkeypatch):
    # Production-shaped routing (VERDICT r04 weak #3): NO threshold
    # override — the default ring_min_seq (2048) must be crossed by a
    # canvas whose top attention level is 4096 tokens, the same class as
    # an SDXL 1024^2 job (tiny VAE downsamples 2x, so 128^2 -> 64^2
    # latents -> 4096 tokens).
    from chiaswarm_tpu.parallel import ring as ring_mod

    calls = []
    orig = ring_mod.ring_shard_map

    def spy(*a, **k):
        calls.append(1)
        return orig(*a, **k)

    monkeypatch.setattr(ring_mod, "ring_shard_map", spy)
    chipset = ChipSet(jax.devices(), seq=2)
    pipe = SDPipeline("test/tiny-sd", chipset=chipset)
    imgs, _ = pipe.run(
        prompt="x", height=128, width=128, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert len(imgs) == 1
    assert calls, "4096-token self-attention did not cross the default ring threshold"
