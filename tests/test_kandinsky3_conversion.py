"""Kandinsky 3 conversion contract (VERDICT r03 missing #1, next #2).

No diffusers in this environment, so the checkpoint side is the torch
mirror in torch_unet_ref.py (Kandinsky3UNetT, exact diffusers key names):
random torch init -> state dict -> convert -> flax forward must equal the
torch forward. Config inference is pinned on the same state dict, and a
full synthetic repo (UNet + MoVQ + T5) must pass `initialize --check`
AND serve txt2img end-to-end with converted weights.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chiaswarm_tpu.models.conversion import convert_kandinsky3_unet
from chiaswarm_tpu.models.unet_kandinsky3 import (
    TINY_K3_UNET,
    Kandinsky3UNet,
)

sys.path.insert(0, os.path.dirname(__file__))

torch = pytest.importorskip("torch")

from torch_unet_ref import Kandinsky3UNetT  # noqa: E402


def _state_numpy(module) -> dict:
    return {k: v.detach().numpy() for k, v in module.state_dict().items()}


@pytest.fixture(scope="module")
def mirror():
    torch.manual_seed(30)
    m = Kandinsky3UNetT(TINY_K3_UNET)
    m.eval()
    return m


def test_k3_config_inferred_from_checkpoint(mirror):
    cfg, _ = convert_kandinsky3_unet(
        _state_numpy(mirror),
        {"attention_head_dim": TINY_K3_UNET.attention_head_dim,
         "groups": TINY_K3_UNET.groups},
    )
    assert cfg == TINY_K3_UNET


def test_k3_unet_torch_parity(mirror):
    """Converted mirror weights drive the flax graph to the torch output —
    validates the rename map, the ConvTranspose layout special-case, the
    conditional group norms, masked attention, and the skip wiring."""
    cfg, params = convert_kandinsky3_unet(
        _state_numpy(mirror),
        {"attention_head_dim": TINY_K3_UNET.attention_head_dim,
         "groups": TINY_K3_UNET.groups},
    )
    rng = np.random.default_rng(31)
    b, hw, s = 2, 16, 8
    sample = rng.standard_normal((b, hw, hw, cfg.in_channels)).astype(
        np.float32
    )
    t = np.asarray([3.0, 250.0], np.float32)
    ctx = rng.standard_normal((b, s, cfg.encoder_hid_dim)).astype(np.float32)
    mask = np.ones((b, s), np.float32)
    mask[0, 5:] = 0.0  # ragged row exercises the mask path end-to-end

    with torch.no_grad():
        out_t = mirror(
            torch.from_numpy(sample).permute(0, 3, 1, 2),
            torch.from_numpy(t),
            torch.from_numpy(ctx),
            torch.from_numpy(mask),
        ).permute(0, 2, 3, 1).numpy()

    out_f = Kandinsky3UNet(cfg).apply(
        {"params": params}, jnp.asarray(sample), jnp.asarray(t),
        jnp.asarray(ctx), jnp.asarray(mask),
    )
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=2e-4, rtol=1e-3)


def _t5_synth_state(params) -> dict:
    """Invert convert_t5: flax T5Encoder params -> transformers
    T5EncoderModel key layout."""
    state = {
        "shared.weight": np.asarray(params["token_embedding"]["embedding"]),
        "encoder.final_layer_norm.weight": np.asarray(
            params["final_norm"]["scale"]
        ),
    }
    i = 0
    while f"block_{i}" in params:
        b = params[f"block_{i}"]
        pre = f"encoder.block.{i}.layer"
        state[f"{pre}.0.layer_norm.weight"] = np.asarray(
            b["attn_norm"]["scale"]
        )
        for p in ("q", "k", "v", "o"):
            state[f"{pre}.0.SelfAttention.{p}.weight"] = np.ascontiguousarray(
                np.asarray(b["attention"][p]["kernel"]).T
            )
        if "relative_attention_bias" in b["attention"]:
            state[f"{pre}.0.SelfAttention.relative_attention_bias.weight"] = (
                np.asarray(b["attention"]["relative_attention_bias"])
            )
        state[f"{pre}.1.layer_norm.weight"] = np.asarray(b["ff_norm"]["scale"])
        for p in ("wi_0", "wi_1", "wo"):
            state[f"{pre}.1.DenseReluDense.{p}.weight"] = np.ascontiguousarray(
                np.asarray(b[p]["kernel"]).T
            )
        i += 1
    return state


def test_full_k3_repo_check_and_pipeline(sdaas_root, tmp_path):
    """A complete synthetic Kandinsky 3 repo — torch-mirror UNet,
    synthetic MoVQ and FLAN-UL2-shaped T5 — passes `initialize --check`
    AND serves a txt2img job through Kandinsky3Pipeline with converted
    weights (reference swarm/test.py:130-147)."""
    from safetensors.numpy import save_file

    from test_kandinsky_conversion import MOVQ_SUBS, _synth_state
    from chiaswarm_tpu.initialize import verify_local_model
    from chiaswarm_tpu.models import movq as movq_mod
    from chiaswarm_tpu.models.t5 import TINY_T5, T5Encoder
    from chiaswarm_tpu.pipelines.kandinsky3 import Kandinsky3Pipeline
    from chiaswarm_tpu.settings import Settings, save_settings

    name = "kandinsky-community/kandinsky-3"
    root = tmp_path / "models"
    save_settings(Settings(model_root_dir=str(root)))
    repo = root / name
    torch.manual_seed(32)

    (repo / "unet").mkdir(parents=True)
    save_file(
        _state_numpy(Kandinsky3UNetT(TINY_K3_UNET)),
        str(repo / "unet" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "unet" / "config.json").write_text(json.dumps({
        "attention_head_dim": TINY_K3_UNET.attention_head_dim,
        "groups": TINY_K3_UNET.groups,
    }))

    movq = movq_mod.MoVQ(movq_mod.TINY_MOVQ)
    mparams = movq.init(jax.random.key(33), jnp.zeros((1, 16, 16, 3)))[
        "params"
    ]
    (repo / "movq").mkdir(parents=True)
    flat = {}
    for k, v in _synth_state(mparams, MOVQ_SUBS).items():
        flat[k] = np.asarray(v)
    save_file(
        flat, str(repo / "movq" / "diffusion_pytorch_model.safetensors")
    )
    (repo / "movq" / "config.json").write_text(json.dumps({
        "block_out_channels": list(movq_mod.TINY_MOVQ.block_out_channels),
        "layers_per_block": movq_mod.TINY_MOVQ.layers_per_block,
        "norm_num_groups": movq_mod.TINY_MOVQ.norm_num_groups,
        "latent_channels": movq_mod.TINY_MOVQ.latent_channels,
        "vq_embed_dim": movq_mod.TINY_MOVQ.vq_embed_dim,
    }))

    t5_params = T5Encoder(TINY_T5).init(
        jax.random.key(34), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    (repo / "text_encoder").mkdir(parents=True)
    save_file(
        _t5_synth_state(t5_params),
        str(repo / "text_encoder" / "model.safetensors"),
    )
    (repo / "text_encoder" / "config.json").write_text(json.dumps({
        "vocab_size": TINY_T5.vocab_size, "d_model": TINY_T5.d_model,
        "d_kv": TINY_T5.d_kv, "num_heads": TINY_T5.num_heads,
        "d_ff": TINY_T5.d_ff, "num_layers": TINY_T5.num_layers,
    }))

    report = verify_local_model(name, root)
    assert report is not None
    assert set(report) == {"unet", "movq", "text_encoder"}

    pipe = Kandinsky3Pipeline(name)
    images, cfg_out = pipe.run(
        prompt="a red fox", height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(35),
    )
    assert len(images) == 1 and images[0].size == (64, 64)
    assert cfg_out["pipeline"] == "Kandinsky3Pipeline"
