"""MPEG Layer I encoder tests: bitstream validity by header parse (pure
python) and end-to-end SNR against a real third-party decoder (pygame's
libmpg123 over ctypes), closing VERDICT r03 item 6 — mp3-family audio
artifacts with content types reflecting reality.
"""

import numpy as np
import pytest

from chiaswarm_tpu.toolbox.mpeg_audio import (
    SUPPORTED_RATES,
    encode_layer1,
    encode_mpeg_buffer,
)

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from mpg123_ref import find_libmpg123  # noqa: E402

needs_mpg123 = pytest.mark.skipif(
    find_libmpg123() is None, reason="libmpg123 not available"
)

_BITRATES_V1 = [0, 32, 64, 96, 128, 160, 192, 224,
                256, 288, 320, 352, 384, 416, 448]
_BITRATES_V2 = [0, 32, 48, 56, 64, 80, 96, 112,
                128, 144, 160, 176, 192, 224, 256]
_FS_V1 = {0: 44100, 1: 48000, 2: 32000}
_FS_V2 = {0: 22050, 1: 24000, 2: 16000}


def _tone(rate: int, seconds: float = 1.5) -> np.ndarray:
    t = np.arange(int(rate * seconds)) / rate
    rng = np.random.default_rng(7)
    x = (0.5 * np.sin(2 * np.pi * 440 * t)
         + 0.2 * np.sin(2 * np.pi * 2333 * t)
         + 0.03 * rng.standard_normal(len(t)))
    return (x / np.abs(x).max() * 0.8).astype(np.float32)


def _walk_frames(data: bytes):
    """Parse frame headers, asserting sync integrity; yields header fields."""
    pos = 0
    while pos + 4 <= len(data):
        h = data[pos: pos + 4]
        assert h[0] == 0xFF and (h[1] & 0xE0) == 0xE0, f"lost sync at {pos}"
        version = (h[1] >> 3) & 0x3
        layer = (h[1] >> 1) & 0x3
        assert layer == 3, "Layer I"
        br_idx = (h[2] >> 4) & 0xF
        fs_idx = (h[2] >> 2) & 0x3
        padding = (h[2] >> 1) & 0x1
        if version == 3:
            bitrate, fs = _BITRATES_V1[br_idx] * 1000, _FS_V1[fs_idx]
        else:
            assert version == 2
            bitrate, fs = _BITRATES_V2[br_idx] * 1000, _FS_V2[fs_idx]
        slots = 12 * bitrate // fs + padding
        yield {"version": version, "bitrate": bitrate, "fs": fs,
               "slots": slots}
        pos += slots * 4
    assert pos == len(data), "stream ends mid-frame"


@pytest.mark.parametrize("rate", SUPPORTED_RATES)
def test_stream_structure(rate):
    data = encode_layer1(_tone(rate, 0.5), rate)
    frames = list(_walk_frames(data))
    assert len(frames) >= int(0.5 * rate / 384)
    assert all(f["fs"] == rate for f in frames)
    # whole stream is frame-aligned and every header agrees
    assert len({f["bitrate"] for f in frames}) == 1


def test_buffer_contract():
    buf = encode_mpeg_buffer(_tone(16000, 0.2), 16000)
    data = buf.read()
    assert data[:1] == b"\xff"
    assert buf.tell() == len(data)


def test_unsupported_rate_raises():
    with pytest.raises(ValueError):
        encode_layer1(np.zeros(100), 12345)


def test_stereo_downmix_and_overload():
    # [n, 2] input and amplitude > 1 both normalise instead of crashing
    x = np.stack([_tone(16000, 0.3)] * 2, axis=1) * 2.5
    data = encode_layer1(x, 16000)
    assert len(list(_walk_frames(data))) > 0


@needs_mpg123
@pytest.mark.parametrize("rate", SUPPORTED_RATES)
def test_decodes_with_real_decoder(rate):
    from mpg123_ref import decode, roundtrip_snr_db

    x = _tone(rate)
    pcm, decoded_rate = decode(encode_layer1(x, rate))
    assert decoded_rate == rate
    assert abs(len(pcm) - len(x)) < 2 * 384 + 512  # frame + filter padding
    assert roundtrip_snr_db(x, pcm[:, 0]) > 35.0


@needs_mpg123
def test_high_bitrate_near_transparent():
    from mpg123_ref import decode, roundtrip_snr_db

    x = _tone(16000)
    pcm, _ = decode(encode_layer1(x, 16000, bitrate_kbps=256))
    assert roundtrip_snr_db(x, pcm[:, 0]) > 70.0


@needs_mpg123
def test_silence_stays_silent():
    from mpg123_ref import decode

    pcm, _ = decode(encode_layer1(np.zeros(16000, np.float32), 16000))
    assert np.abs(pcm).max() < 1e-4


def test_audio_artifact_contract():
    from chiaswarm_tpu.pipelines.audio import audio_artifact

    # off-table rates resample to the nearest MPEG rate, still audio/mpeg,
    # and the returned rate reflects the stream
    buf, produced, rate = audio_artifact(np.zeros(1000, np.float32), 12345)
    assert produced == "audio/mpeg" and rate == 16000
    assert list(_walk_frames(buf.read()))[0]["fs"] == 16000

    buf, produced, rate = audio_artifact(
        np.zeros(1000, np.float32), 16000, content_type="audio/wav")
    assert produced == "audio/wav" and rate == 16000
    assert buf.read(4) == b"RIFF"

    buf, produced, rate = audio_artifact(_tone(16000, 0.2), 16000)
    assert produced == "audio/mpeg" and rate == 16000
    head = buf.read(2)
    assert head[0] == 0xFF and (head[1] & 0xE0) == 0xE0


def test_ffmpeg_escape_hatch(monkeypatch, tmp_path):
    """CHIASWARM_FFMPEG_AUDIO=1 routes through a PATH ffmpeg when present
    and falls back to the built-in Layer-I encoder when absent."""
    import os

    from chiaswarm_tpu.pipelines.audio import audio_artifact

    monkeypatch.setenv("CHIASWARM_FFMPEG_AUDIO", "1")
    real_path = os.environ.get("PATH", "")

    # no ffmpeg on PATH -> built-in encoder still produces audio/mpeg
    monkeypatch.setenv("PATH", str(tmp_path / "nowhere"))
    buf, produced, rate = audio_artifact(_tone(16000, 0.1), 16000)
    assert produced == "audio/mpeg"
    head = buf.read(2)
    assert head[0] == 0xFF and (head[1] & 0xE0) == 0xE0

    # fake ffmpeg FIRST on the real PATH (the script still needs cat) ->
    # its stdout becomes the artifact verbatim
    fake = tmp_path / "bin"
    fake.mkdir()
    script = fake / "ffmpeg"
    script.write_text("#!/bin/sh\ncat > /dev/null\nprintf 'MP3!'\n")
    script.chmod(0o755)
    monkeypatch.setenv("PATH", str(fake) + os.pathsep + real_path)
    buf, produced, rate = audio_artifact(_tone(16000, 0.1), 16000)
    assert produced == "audio/mpeg"
    assert buf.read() == b"MP3!"
