"""AudioLDM2 conversion contract — the last family without a real-weight
serving path (round 4 closes the skip list).

Ground truth mix: GPT-2 and the text towers are validated against REAL
transformers modules (exact state dicts); the dual-conditioned UNet and
the projection model against exact-key torch mirrors; and a full
synthetic cvssp/audioldm2-shaped repo (including the ClapModel AUDIO
tower the conversion must filter out) passes `initialize --check` and
serves a txt2audio job end-to-end.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(__file__))

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from torch_unet_ref import ResnetT, TimestepEmbeddingT, timestep_embedding_t  # noqa: E402

from chiaswarm_tpu.models.audioldm2_unet import (  # noqa: E402
    TINY_AUDIOLDM2_UNET,
    AudioLDM2Projection,
    AudioLDM2UNet,
)
from chiaswarm_tpu.models.conversion import (  # noqa: E402
    convert_audioldm2_projection,
    convert_audioldm2_unet,
    convert_gpt2,
    infer_audioldm2_unet_config,
)
from chiaswarm_tpu.models.gpt2 import GPT2Config, GPT2Model  # noqa: E402


def _state_numpy(module) -> dict:
    return {k: v.detach().numpy() for k, v in module.state_dict().items()}


def test_gpt2_transformers_parity():
    from transformers import GPT2Config as HFGPT2Config
    from transformers import GPT2Model as HFGPT2Model

    torch.manual_seed(90)
    hf = HFGPT2Model(HFGPT2Config(
        n_embd=32, n_layer=2, n_head=4, n_positions=64, vocab_size=100
    ))
    hf.eval()
    params = convert_gpt2(_state_numpy(hf))
    rng = np.random.default_rng(91)
    x = rng.standard_normal((2, 7, 32)).astype(np.float32)
    mask = np.ones((2, 7), np.float32)
    mask[1, 5:] = 0
    with torch.no_grad():
        out_t = hf(
            inputs_embeds=torch.from_numpy(x),
            attention_mask=torch.from_numpy(mask),
        ).last_hidden_state.numpy()
    out_f = GPT2Model(GPT2Config(32, 2, 4, 64)).apply(
        {"params": params}, jnp.asarray(x), jnp.asarray(mask)
    )
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=2e-4, rtol=1e-3)


class _MaskedAttnT(nn.Module):
    def __init__(self, ch, heads, head_dim, kv_dim=None):
        super().__init__()
        inner = heads * head_dim
        self.heads, self.head_dim = heads, head_dim
        self.to_q = nn.Linear(ch, inner, bias=False)
        self.to_k = nn.Linear(kv_dim or ch, inner, bias=False)
        self.to_v = nn.Linear(kv_dim or ch, inner, bias=False)
        self.to_out = nn.ModuleList([nn.Linear(inner, ch)])

    def forward(self, q_in, kv_in, mask=None):
        b, n, _ = q_in.shape
        s = kv_in.shape[1]
        q = self.to_q(q_in).view(b, n, self.heads, self.head_dim).transpose(1, 2)
        k = self.to_k(kv_in).view(b, s, self.heads, self.head_dim).transpose(1, 2)
        v = self.to_v(kv_in).view(b, s, self.heads, self.head_dim).transpose(1, 2)
        logits = q @ k.transpose(-1, -2) * self.head_dim ** -0.5
        if mask is not None:
            logits = logits.masked_fill(
                ~(mask[:, None, None, :] != 0), float(-1e9)
            )
        out = logits.softmax(-1) @ v
        return self.to_out[0](out.transpose(1, 2).reshape(b, n, -1))


class _GEGLUT(nn.Module):
    def __init__(self, ch):
        super().__init__()
        self.proj = nn.Linear(ch, 8 * ch)

    def forward(self, x):
        # diffusers GEGLU: FIRST half is the value, SECOND the gelu gate
        value, gate = self.proj(x).chunk(2, dim=-1)
        return value * F.gelu(gate)


class _ALDM2TransformerT(nn.Module):
    """AudioLDM2's single-block Transformer2D with exact diffusers keys."""

    def __init__(self, ch, heads, head_dim, cross_dim, groups):
        super().__init__()
        self.norm = nn.GroupNorm(groups, ch, eps=1e-6)
        self.proj_in = nn.Linear(ch, ch)
        blk = nn.Module()
        blk.norm1 = nn.LayerNorm(ch)
        blk.attn1 = _MaskedAttnT(ch, heads, head_dim)
        blk.norm2 = nn.LayerNorm(ch)
        blk.attn2 = _MaskedAttnT(ch, heads, head_dim, cross_dim)
        blk.norm3 = nn.LayerNorm(ch)
        ff = nn.Module()
        ff.net = nn.ModuleList([_GEGLUT(ch), nn.Identity(),
                                nn.Linear(4 * ch, ch)])
        blk.ff = ff
        self.transformer_blocks = nn.ModuleList([blk])
        self.proj_out = nn.Linear(ch, ch)

    def forward(self, x, ctx, mask):
        b, c, h, w = x.shape
        residual = x
        hidden = self.norm(x).permute(0, 2, 3, 1).reshape(b, h * w, c)
        hidden = self.proj_in(hidden)
        blk = self.transformer_blocks[0]
        normed = blk.norm1(hidden)
        hidden = hidden + blk.attn1(normed, normed)
        hidden = hidden + blk.attn2(blk.norm2(hidden), ctx, mask)
        hidden = hidden + blk.ff.net[2](blk.ff.net[0](blk.norm3(hidden)))
        hidden = self.proj_out(hidden)
        return hidden.reshape(b, h, w, c).permute(0, 3, 1, 2) + residual


class _Stage(nn.Module):
    pass


class AudioLDM2UNetT(nn.Module):
    """Exact-key diffusers AudioLDM2UNet2DConditionModel mirror for the
    tiny config (paired per-layer cross transformers)."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        blocks = cfg.block_out_channels
        temb = blocks[0] * 4
        g = cfg.norm_num_groups
        hd = cfg.attention_head_dim
        self.time_embedding = TimestepEmbeddingT(blocks[0], temb)
        self.conv_in = nn.Conv2d(cfg.in_channels, blocks[0], 3, padding=1)
        self.down_blocks = nn.ModuleList()
        ch = blocks[0]
        n = len(blocks)
        for bidx, out_ch in enumerate(blocks):
            stage = _Stage()
            stage.resnets = nn.ModuleList()
            if cfg.attention[bidx]:
                stage.attentions = nn.ModuleList()
            for i in range(cfg.layers_per_block):
                stage.resnets.append(
                    ResnetT(ch if i == 0 else out_ch, out_ch, temb)
                )
                if cfg.attention[bidx]:
                    for cross in cfg.cross_attention_dims:
                        stage.attentions.append(_ALDM2TransformerT(
                            out_ch, hd, max(1, out_ch // hd), cross, g
                        ))
            if bidx != n - 1:
                down = _Stage()
                down.conv = nn.Conv2d(out_ch, out_ch, 3, stride=2, padding=1)
                stage.downsamplers = nn.ModuleList([down])
            self.down_blocks.append(stage)
            ch = out_ch

        mid = _Stage()
        mid.resnets = nn.ModuleList(
            [ResnetT(blocks[-1], blocks[-1], temb),
             ResnetT(blocks[-1], blocks[-1], temb)]
        )
        mid.attentions = nn.ModuleList([
            _ALDM2TransformerT(blocks[-1], hd, max(1, blocks[-1] // hd),
                               cross, g)
            for cross in cfg.cross_attention_dims
        ])
        self.mid_block = mid

        skip_chs = [blocks[0]]
        for bidx, out_ch in enumerate(blocks):
            skip_chs += [out_ch] * cfg.layers_per_block
            if bidx != n - 1:
                skip_chs.append(out_ch)
        self.up_blocks = nn.ModuleList()
        ch = blocks[-1]
        for bidx, out_ch in enumerate(reversed(blocks)):
            rev = n - 1 - bidx
            stage = _Stage()
            stage.resnets = nn.ModuleList()
            if cfg.attention[rev]:
                stage.attentions = nn.ModuleList()
            for i in range(cfg.layers_per_block + 1):
                skip = skip_chs.pop()
                stage.resnets.append(ResnetT(ch + skip, out_ch, temb))
                if cfg.attention[rev]:
                    for cross in cfg.cross_attention_dims:
                        stage.attentions.append(_ALDM2TransformerT(
                            out_ch, hd, max(1, out_ch // hd), cross, g
                        ))
                ch = out_ch
            if bidx != n - 1:
                up = _Stage()
                up.conv = nn.Conv2d(out_ch, out_ch, 3, padding=1)
                stage.upsamplers = nn.ModuleList([up])
            self.up_blocks.append(stage)
        self.conv_norm_out = nn.GroupNorm(g, blocks[0], eps=1e-5)
        self.conv_out = nn.Conv2d(blocks[0], cfg.out_channels, 3, padding=1)

    def forward(self, sample, timesteps, ctx0, m0, ctx1, m1):
        cfg = self.cfg
        ctxs = ((ctx0, m0), (ctx1, m1))
        temb = self.time_embedding(
            timestep_embedding_t(timesteps, cfg.block_out_channels[0])
        )
        x = self.conv_in(sample)
        skips = [x]
        for stage in self.down_blocks:
            for i, resnet in enumerate(stage.resnets):
                x = resnet(x, temb)
                if hasattr(stage, "attentions"):
                    for idx, (ctx, m) in enumerate(ctxs):
                        x = stage.attentions[i * 2 + idx](x, ctx, m)
                skips.append(x)
            if hasattr(stage, "downsamplers"):
                x = stage.downsamplers[0].conv(x)
                skips.append(x)
        m = self.mid_block
        x = m.resnets[0](x, temb)
        for idx, (ctx, msk) in enumerate(ctxs):
            x = m.attentions[idx](x, ctx, msk)
        x = m.resnets[1](x, temb)
        for stage in self.up_blocks:
            for i, resnet in enumerate(stage.resnets):
                x = torch.cat([x, skips.pop()], dim=1)
                x = resnet(x, temb)
                if hasattr(stage, "attentions"):
                    for idx, (ctx, msk) in enumerate(ctxs):
                        x = stage.attentions[i * 2 + idx](x, ctx, msk)
            if hasattr(stage, "upsamplers"):
                x = F.interpolate(x, scale_factor=2.0, mode="nearest")
                x = stage.upsamplers[0].conv(x)
        return self.conv_out(F.silu(self.conv_norm_out(x)))


class AudioLDM2ProjectionT(nn.Module):
    def __init__(self, d0, d1, lm):
        super().__init__()
        self.projection = nn.Linear(d0, lm)
        self.projection_1 = nn.Linear(d1, lm)
        self.sos_embed = nn.Parameter(torch.randn(lm))
        self.eos_embed = nn.Parameter(torch.randn(lm))
        self.sos_embed_1 = nn.Parameter(torch.randn(lm))
        self.eos_embed_1 = nn.Parameter(torch.randn(lm))

    def forward(self, h0, m0, h1, m1):
        b = h0.shape[0]
        h0 = self.projection(h0)
        h1 = self.projection_1(h1)
        ones = m0.new_ones((b, 1))
        seq = torch.cat([
            self.sos_embed.expand(b, 1, -1), h0,
            self.eos_embed.expand(b, 1, -1),
            self.sos_embed_1.expand(b, 1, -1), h1,
            self.eos_embed_1.expand(b, 1, -1),
        ], dim=1)
        mask = torch.cat([ones, m0, ones, ones, m1, ones], dim=-1)
        return seq, mask


@pytest.fixture(scope="module")
def mirror():
    torch.manual_seed(92)
    m = AudioLDM2UNetT(TINY_AUDIOLDM2_UNET)
    m.eval()
    return m


def test_audioldm2_config_inferred(mirror):
    cfg = infer_audioldm2_unet_config(
        _state_numpy(mirror),
        {"attention_head_dim": TINY_AUDIOLDM2_UNET.attention_head_dim,
         "norm_num_groups": TINY_AUDIOLDM2_UNET.norm_num_groups},
    )
    assert cfg == TINY_AUDIOLDM2_UNET


def test_audioldm2_unet_torch_parity(mirror):
    cfg = TINY_AUDIOLDM2_UNET
    params = convert_audioldm2_unet(_state_numpy(mirror))
    rng = np.random.default_rng(93)
    sample = rng.standard_normal((2, 16, 8, cfg.in_channels)).astype(
        np.float32
    )
    t = np.asarray([3.0, 400.0], np.float32)
    c0 = rng.standard_normal((2, 6, cfg.cross_attention_dims[0])).astype(
        np.float32
    )
    m0 = np.ones((2, 6), np.float32)
    m0[0, 4:] = 0
    c1 = rng.standard_normal((2, 9, cfg.cross_attention_dims[1])).astype(
        np.float32
    )
    m1 = np.ones((2, 9), np.float32)
    m1[1, 7:] = 0
    with torch.no_grad():
        out_t = mirror(
            torch.from_numpy(sample).permute(0, 3, 1, 2),
            torch.from_numpy(t),
            torch.from_numpy(c0), torch.from_numpy(m0),
            torch.from_numpy(c1), torch.from_numpy(m1),
        ).permute(0, 2, 3, 1).numpy()
    out_f = AudioLDM2UNet(cfg).apply(
        {"params": params}, jnp.asarray(sample), jnp.asarray(t),
        jnp.asarray(c0), jnp.asarray(m0), jnp.asarray(c1), jnp.asarray(m1),
    )
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=3e-4, rtol=1e-3)


def test_audioldm2_projection_parity():
    torch.manual_seed(94)
    tm = AudioLDM2ProjectionT(12, 16, 32)
    tm.eval()
    params = convert_audioldm2_projection(_state_numpy(tm))
    rng = np.random.default_rng(95)
    h0 = rng.standard_normal((2, 1, 12)).astype(np.float32)
    m0 = np.ones((2, 1), np.float32)
    h1 = rng.standard_normal((2, 5, 16)).astype(np.float32)
    m1 = np.ones((2, 5), np.float32)
    m1[0, 3:] = 0
    with torch.no_grad():
        seq_t, mask_t = tm(
            torch.from_numpy(h0), torch.from_numpy(m0),
            torch.from_numpy(h1), torch.from_numpy(m1),
        )
    seq_f, mask_f = AudioLDM2Projection(32).apply(
        {"params": params}, jnp.asarray(h0), jnp.asarray(m0),
        jnp.asarray(h1), jnp.asarray(m1),
    )
    np.testing.assert_allclose(np.asarray(seq_f), seq_t.numpy(), atol=1e-5)
    np.testing.assert_array_equal(np.asarray(mask_f), mask_t.numpy())


def test_full_audioldm2_repo_check_and_pipeline(sdaas_root, tmp_path):
    """A complete synthetic cvssp/audioldm2-shaped repo — mirror UNet +
    projection, REAL transformers ClapModel (WITH the audio tower the
    conversion must filter), T5EncoderModel, GPT2Model, SpeechT5HifiGan,
    mirror mel VAE — passes `initialize --check` AND serves a txt2audio
    job end-to-end with converted weights."""
    import dataclasses

    from safetensors.numpy import save_file
    from transformers import (
        ClapAudioConfig,
        ClapConfig,
        ClapModel,
        ClapTextConfig as HFClapTextConfig,
        GPT2Config as HFGPT2Config,
        GPT2Model as HFGPT2Model,
        SpeechT5HifiGan,
        SpeechT5HifiGanConfig,
        T5Config as HFT5Config,
        T5EncoderModel,
    )

    from torch_unet_ref import AutoencoderKLT

    from chiaswarm_tpu.initialize import verify_local_model
    from chiaswarm_tpu.models import configs as cfgs
    from chiaswarm_tpu.pipelines.audio import run_audioldm
    from chiaswarm_tpu.settings import Settings, save_settings

    name = "cvssp/audioldm2"
    root = tmp_path / "models"
    save_settings(Settings(model_root_dir=str(root)))
    repo = root / name
    torch.manual_seed(96)
    cfg = TINY_AUDIOLDM2_UNET

    (repo / "unet").mkdir(parents=True)
    save_file(
        _state_numpy(AudioLDM2UNetT(cfg)),
        str(repo / "unet" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "unet" / "config.json").write_text(json.dumps({
        "attention_head_dim": cfg.attention_head_dim,
        "norm_num_groups": cfg.norm_num_groups,
    }))

    clap = ClapModel(ClapConfig.from_text_audio_configs(
        HFClapTextConfig(
            vocab_size=1000, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=80, type_vocab_size=1, pad_token_id=1,
            projection_dim=12,
        ),
        ClapAudioConfig(
            spec_size=32, patch_size=4, patch_stride=[4, 4], num_mel_bins=8,
            window_size=2, depths=[1, 1], num_attention_heads=[1, 1],
            patch_embeds_hidden_size=16, hidden_size=32, projection_dim=12,
        ),
        projection_dim=12,
    ))
    (repo / "text_encoder").mkdir(parents=True)
    save_file(
        _state_numpy(clap),
        str(repo / "text_encoder" / "model.safetensors"),
    )
    (repo / "text_encoder" / "config.json").write_text(json.dumps({
        "projection_dim": 12,
        "text_config": {
            "vocab_size": 1000, "hidden_size": 32, "num_hidden_layers": 2,
            "num_attention_heads": 4, "intermediate_size": 64,
            "max_position_embeddings": 80,
        },
    }))

    t5 = T5EncoderModel(HFT5Config(
        vocab_size=1000, d_model=cfg.cross_attention_dims[1], d_kv=8,
        num_heads=4, d_ff=64, num_layers=2, num_decoder_layers=0,
        feed_forward_proj="gated-gelu",  # the FLAN layout convert_t5 maps
    ))
    (repo / "text_encoder_2").mkdir(parents=True)
    save_file(
        _state_numpy(t5),
        str(repo / "text_encoder_2" / "model.safetensors"),
    )
    (repo / "text_encoder_2" / "config.json").write_text(json.dumps({
        "vocab_size": 1000, "d_model": cfg.cross_attention_dims[1],
        "d_kv": 8, "num_heads": 4, "d_ff": 64, "num_layers": 2,
    }))

    gpt2 = HFGPT2Model(HFGPT2Config(
        n_embd=cfg.cross_attention_dims[0], n_layer=2, n_head=4,
        n_positions=64, vocab_size=100,
    ))
    (repo / "language_model").mkdir(parents=True)
    save_file(
        _state_numpy(gpt2),
        str(repo / "language_model" / "model.safetensors"),
    )
    (repo / "language_model" / "config.json").write_text(json.dumps({
        "n_embd": cfg.cross_attention_dims[0], "n_layer": 2, "n_head": 4,
        "n_positions": 64,
    }))

    proj = AudioLDM2ProjectionT(
        12, cfg.cross_attention_dims[1], cfg.cross_attention_dims[0]
    )
    (repo / "projection_model").mkdir(parents=True)
    save_file(
        _state_numpy(proj),
        str(repo / "projection_model" / "model.safetensors"),
    )

    vae_cfg = dataclasses.replace(
        cfgs.TINY_VAE, in_channels=1, latent_channels=cfg.in_channels,
    )
    (repo / "vae").mkdir(parents=True)
    save_file(
        _state_numpy(AutoencoderKLT(vae_cfg)),
        str(repo / "vae" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "vae" / "config.json").write_text(
        json.dumps({"scaling_factor": 0.9227})
    )

    voc_shape = dict(
        model_in_dim=8, upsample_initial_channel=16,
        upsample_rates=[4, 4], upsample_kernel_sizes=[8, 8],
        resblock_kernel_sizes=[3], resblock_dilation_sizes=[[1, 3]],
    )
    (repo / "vocoder").mkdir(parents=True)
    save_file(
        _state_numpy(SpeechT5HifiGan(SpeechT5HifiGanConfig(
            **voc_shape, normalize_before=True,
        ))),
        str(repo / "vocoder" / "model.safetensors"),
    )
    (repo / "vocoder" / "config.json").write_text(json.dumps(voc_shape))

    tok_dir = repo / "tokenizer"
    tok_dir.mkdir()
    vocab = {"<s>": 0, "<pad>": 1, "</s>": 2, "<unk>": 3, "rain": 4,
             "Ġon": 5, "Ġroof": 6}
    (tok_dir / "vocab.json").write_text(json.dumps(vocab))
    (tok_dir / "merges.txt").write_text("#version: 0.2\n")
    (tok_dir / "tokenizer_config.json").write_text(
        json.dumps({"tokenizer_class": "RobertaTokenizer",
                    "model_max_length": 80})
    )

    report = verify_local_model(name, root)
    assert report is not None
    assert set(report) == {
        "unet", "language_model", "text_encoder", "text_encoder_2",
        "projection_model", "vae", "vocoder",
    }
    assert all(v > 0 for v in report.values())

    artifacts, config = run_audioldm(
        "cpu", name, prompt="rain on roof",
        parameters={},
        pipeline_type="AudioLDM2Pipeline",
        num_inference_steps=2, audio_length_in_s=0.5,
        rng=jax.random.key(7),
    )
    assert artifacts["primary"]["blob"]
    assert config["pipeline"] == "AudioLDM2Pipeline"
