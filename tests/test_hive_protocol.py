"""Wire-protocol conformance: FakeHive, the real hive_server, and a
PROMOTED STANDBY all answer identically to the worker's own client.

Every assertion here runs against all three backends (parametrized),
driven through `chiaswarm_tpu.hive.HiveClient` — the exact code a
production worker uses — plus raw aiohttp where the contract is about
status codes and payload shapes. The fake can therefore never drift
from the real coordinator's wire contract again, and a standby that
replicated + promoted (ISSUE 7) is pinned to the same contract as a
born-primary hive: a behavior change in any backend breaks this suite
until the others follow.
"""

import asyncio
import base64
import dataclasses
import hashlib
import json

import aiohttp
import pytest

from chiaswarm_tpu.hive import HiveClient
from chiaswarm_tpu.settings import Settings

from .fake_hive import FakeHive

TOKEN = "conformance-token"


class FakeBackend:
    name = "fake"

    async def start(self):
        self.hive = await FakeHive().start()
        self.hive.expected_token = TOKEN
        return self

    @property
    def uri(self) -> str:
        return self.hive.uri

    def queue_job(self, job: dict) -> None:
        self.hive.add_job(job)

    def refuse(self, message: str) -> None:
        self.hive.refuse_with = message

    def redeliver(self, job: dict) -> None:
        # the fake has no lease clock: re-queueing the same id IS the
        # redelivery (dispatch_attempts persists, so the next hand-out
        # carries attempt 2 — exactly what a reaped lease produces)
        self.hive.add_job(dict(job))

    async def stop(self) -> None:
        await self.hive.stop()


class RealBackend:
    name = "real"

    async def start(self):
        from chiaswarm_tpu.hive_server import HiveServer

        settings = Settings(sdaas_token=TOKEN, hive_port=0,
                            hive_max_jobs_per_poll=8)
        self.server = await HiveServer(settings, port=0).start()
        return self

    @property
    def uri(self) -> str:
        return self.server.api_uri

    def queue_job(self, job: dict) -> None:
        # submission is the coordinator's own surface, not part of the
        # worker-facing wire contract under test — enqueue directly
        self.server.queue.submit(job)

    def refuse(self, message: str) -> None:
        self.server.refuse_with = message

    def redeliver(self, job: dict) -> None:
        _expire_and_reap(self.server, str(job["id"]))

    async def stop(self) -> None:
        await self.server.stop()


def _expire_and_reap(server, job_id: str) -> None:
    """Force the lease reaper's hand: expire the live lease NOW and
    reap, putting the job back at the front of its class exactly as a
    worker death would."""
    lease = server.leases.get(job_id)
    assert lease is not None, f"no live lease for {job_id}"
    lease.expires_at = 0.0
    server.leases.reap(server.queue)


class PromotedBackend:
    """A standby that replicated a (briefly live) primary and promoted
    itself after the primary stopped — the protocol surface a worker
    lands on after a failover. Conformance against it proves promotion
    produces a full primary, not a half-serving replica."""

    name = "promoted"

    async def start(self):
        from chiaswarm_tpu.hive_server import HiveServer
        from chiaswarm_tpu.hive_server.replication import StandbyHive

        base = Settings(sdaas_token=TOKEN, hive_port=0,
                        hive_max_jobs_per_poll=8,
                        hive_wal_dir="wal_conf_primary")
        primary = await HiveServer(base, port=0).start()
        self.standby = StandbyHive(
            dataclasses.replace(base, hive_wal_dir="wal_conf_standby"),
            primary_uri=primary.uri, port=0)
        await self.standby.server.start()
        await self.standby.sync_once()
        await primary.stop()
        self.server = await self.standby.promote()
        return self

    @property
    def uri(self) -> str:
        return self.server.api_uri

    def queue_job(self, job: dict) -> None:
        self.server.queue.submit(job)

    def refuse(self, message: str) -> None:
        self.server.refuse_with = message

    def redeliver(self, job: dict) -> None:
        _expire_and_reap(self.server, str(job["id"]))

    async def stop(self) -> None:
        await self.standby.stop()


BACKENDS = {"fake": FakeBackend, "real": RealBackend,
            "promoted": PromotedBackend}


def run_conformance(backend_name: str, scenario):
    """Stand a backend up, run one async scenario against it, tear down."""

    async def _run():
        backend = await BACKENDS[backend_name]().start()
        client = HiveClient(Settings(sdaas_token=TOKEN), backend.uri)
        try:
            return await scenario(backend, client)
        finally:
            await client.close()
            await backend.stop()

    return asyncio.run(_run())


CAPS = {"memory": 16, "gpu": "tpu", "chips": 4, "hbm_gb": 64,
        "slices": 2, "busy_slices": 0, "queue_depth": 0, "topology": "cpux4"}


def echo_job(job_id: str = "conf-1") -> dict:
    return {"id": job_id, "workflow": "echo", "model_name": "none",
            "prompt": job_id}


@pytest.fixture(params=["fake", "real", "promoted"])
def backend_name(request, sdaas_root):
    return request.param


def test_work_hands_out_queued_jobs_then_empties(backend_name):
    async def scenario(backend, client):
        backend.queue_job(echo_job())
        jobs = await client.ask_for_work(dict(CAPS))
        assert isinstance(jobs, list)
        assert [j["id"] for j in jobs] == ["conf-1"]
        # the same job is not handed out twice on the next poll
        assert await client.ask_for_work(dict(CAPS)) == []

    run_conformance(backend_name, scenario)


def test_work_response_shape_is_jobs_list(backend_name):
    async def scenario(backend, client):
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f"{backend.uri}/work",
                    params={"worker_version": "0.1.0", "worker_name": "w"},
                    headers={"Authorization": f"Bearer {TOKEN}"}) as resp:
                assert resp.status == 200
                payload = await resp.json()
        assert isinstance(payload, dict)
        assert isinstance(payload["jobs"], list)

    run_conformance(backend_name, scenario)


def test_refusal_is_400_with_message(backend_name):
    async def scenario(backend, client):
        backend.refuse("worker too slow for this hive")
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f"{backend.uri}/work",
                    params={"worker_version": "0.1.0", "worker_name": "w"},
                    headers={"Authorization": f"Bearer {TOKEN}"}) as resp:
                assert resp.status == 400
                payload = await resp.json()
        assert payload["message"] == "worker too slow for this hive"
        # the client surfaces the refusal as an HTTP error (poll_loop's
        # backoff path), never as an empty job list
        with pytest.raises(aiohttp.ClientResponseError):
            await client.ask_for_work(dict(CAPS))

    run_conformance(backend_name, scenario)


def test_bearer_auth_enforced_on_work_and_results(backend_name):
    async def scenario(backend, client):
        bad = HiveClient(Settings(sdaas_token="wrong-token"), backend.uri)
        try:
            with pytest.raises(aiohttp.ClientResponseError) as err:
                await bad.ask_for_work(dict(CAPS))
            assert err.value.status == 401
            with pytest.raises(Exception):
                await bad.submit_result({"id": "x", "artifacts": {}})
        finally:
            await bad.close()

    run_conformance(backend_name, scenario)


def test_result_ack_is_json_and_duplicate_safe(backend_name):
    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-ack"))
        [job] = await client.ask_for_work(dict(CAPS))
        envelope = {
            "id": job["id"],
            "artifacts": {"primary": {
                "blob": "aGVsbG8=", "content_type": "image/jpeg"}},
            "nsfw": False,
            "worker_version": "0.1.0",
            "pipeline_config": {},
        }
        ack = await client.submit_result(envelope)
        assert isinstance(ack, dict)
        # at-least-once delivery: the outbox may re-POST after a lost
        # ACK, and the hive must answer 200 again, not error
        ack2 = await client.submit_result(dict(envelope))
        assert isinstance(ack2, dict)

    run_conformance(backend_name, scenario)


def test_models_catalog_shape(backend_name):
    async def scenario(backend, client):
        async with aiohttp.ClientSession() as session:
            async with session.get(f"{backend.uri}/models") as resp:
                assert resp.status == 200
                catalog = await resp.json()
        assert isinstance(catalog["models"], list)
        assert isinstance(catalog["language_models"], list)
        for entry in catalog["models"]:
            assert "id" in entry
        # the client's combined view (it also caches models.json, which
        # sdaas_root sandboxes)
        combined = await client.get_models()
        assert isinstance(combined, list)
        assert len(combined) == len(catalog["models"]) + len(
            catalog["language_models"])

    run_conformance(backend_name, scenario)


def test_unknown_query_params_are_ignored(backend_name):
    """Capability advertisement grows over time (resident_models,
    queue_depth, flux_runnable, ...); a hive must never refuse a worker
    for sending a key it does not know."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-extra"))
        # queue_depth is deliberately NOT an example here: it is a KNOWN
        # placement param — a worker advertising more local depth than
        # free slices is saturated, and the real hive answers it with an
        # empty jobs list (dispatch-budget contract, pinned in
        # test_hive_server.py) rather than burying it
        caps = dict(CAPS, resident_models="a/b,c/d",
                    some_future_capability="42")
        jobs = await client.ask_for_work(caps)
        assert [j["id"] for j in jobs] == ["conf-extra"]

    run_conformance(backend_name, scenario)


def test_work_reply_carries_trace_context(backend_name):
    """ISSUE 8: every handed job carries its trace context on the wire —
    {id, attempt, dispatched_wall, queue_wait_s} under the `trace` key —
    so the worker can echo it back inside the envelope and the hive can
    attribute the returning stage spans to the right dispatch attempt.
    Pinned across all three backends so fake_hive cannot drift."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-trace"))
        [job] = await client.ask_for_work(dict(CAPS))
        trace = job["trace"]
        assert isinstance(trace, dict)
        assert trace["id"] == "conf-trace"
        assert isinstance(trace["attempt"], int) and trace["attempt"] >= 1
        assert isinstance(trace["dispatched_wall"], (int, float))
        assert isinstance(trace["queue_wait_s"], (int, float))
        # a solo dispatch carries NO gang key at all — the key's absence
        # is what tells the worker's poll loop to take the classic path
        assert "gang" not in trace

    run_conformance(backend_name, scenario)


def gang_job(i: int) -> dict:
    """Coalesce-compatible txt2img jobs (same model/canvas/steps) — the
    exact shape both the hive's gang scheduler and the worker's
    BatchScheduler bucket together via the shared coalesce module."""
    return {"id": f"conf-gang-{i}", "workflow": "txt2img",
            "model_name": "stabilityai/stable-diffusion-2-1",
            "prompt": f"gang member {i}", "height": 64, "width": 64,
            "num_inference_steps": 2,
            "parameters": {"test_tiny_model": True}}


def test_gang_reply_groups_compatible_jobs(backend_name):
    """ISSUE 9: a poll advertising gang capacity (`gang_rows`) receives
    same-key queued jobs as ONE pre-batched group — every member carries
    `trace.gang = {id, size, index}` with one shared id, the true group
    size, and its position. Pinned across all three backends so
    fake_hive cannot drift from the gang wire contract."""

    async def scenario(backend, client):
        for i in range(3):
            backend.queue_job(gang_job(i))
        jobs = await client.ask_for_work(dict(CAPS, gang_rows=8))
        assert [j["id"] for j in jobs] == [f"conf-gang-{i}" for i in range(3)]
        gangs = [j["trace"]["gang"] for j in jobs]
        assert len({g["id"] for g in gangs}) == 1 and gangs[0]["id"]
        assert all(g["size"] == 3 for g in gangs)
        assert [g["index"] for g in gangs] == [0, 1, 2]
        # each member still carries its OWN per-job trace context — a
        # gang is a dispatch-time grouping, not a merged job
        assert [j["trace"]["id"] for j in jobs] == [j["id"] for j in jobs]

    run_conformance(backend_name, scenario)


def test_adapter_jobs_gang_with_mixed_adapters(backend_name):
    """ISSUE 13: jobs carrying DIFFERENT `lora` adapters (and an
    adapter-free batchmate) on one base model leave as ONE gang — the
    extended coalesce key admits adapters, identity rides per job on
    the wire. Pinned across all three backends so fake_hive cannot
    drift from the adapter-aware grouping."""

    async def scenario(backend, client):
        backend.queue_job(dict(gang_job(0), lora="style-a"))
        backend.queue_job(dict(gang_job(1), lora="style-b"))
        backend.queue_job(gang_job(2))  # adapter-free batchmate
        jobs = await client.ask_for_work(dict(CAPS, gang_rows=8))
        assert [j["id"] for j in jobs] == [f"conf-gang-{i}" for i in range(3)]
        gangs = [j["trace"]["gang"] for j in jobs]
        assert len({g["id"] for g in gangs}) == 1
        assert all(g["size"] == 3 for g in gangs)
        # each member keeps its OWN adapter reference on the wire —
        # adapter identity is per-row data, never merged into the gang
        assert [j.get("lora") for j in jobs] == ["style-a", "style-b", None]

    run_conformance(backend_name, scenario)


def test_declared_rank_bucket_splits_the_gang(backend_name):
    """ISSUE 13: a job declaring an incompatible `lora_rank` keys to a
    different rank bucket and must NOT ride the same gang (the gang's
    stacked factors share one padded rank)."""

    async def scenario(backend, client):
        backend.queue_job(dict(gang_job(0), lora="style-a"))
        ranked = dict(gang_job(1), lora="style-b")
        ranked["parameters"] = dict(ranked["parameters"], lora_rank=64)
        backend.queue_job(ranked)
        jobs = await client.ask_for_work(dict(CAPS, gang_rows=8))
        assert len(jobs) == 2
        by_id = {j["id"]: j for j in jobs}
        g0 = by_id["conf-gang-0"]["trace"].get("gang")
        g1 = by_id["conf-gang-1"]["trace"].get("gang")
        # two different buckets: either solo dispatches or distinct gangs
        assert g0 is None or g1 is None or g0["id"] != g1["id"]

    run_conformance(backend_name, scenario)


def test_no_gang_without_worker_gang_rows(backend_name):
    """A worker that does not advertise `gang_rows` keeps the pre-gang
    contract: jobs may still arrive in one reply, but never marked as a
    gang — a legacy worker must see nothing new on the wire."""

    async def scenario(backend, client):
        for i in range(2):
            backend.queue_job(gang_job(i))
        jobs = await client.ask_for_work(dict(CAPS))
        assert jobs  # at least one handed
        assert all("gang" not in j["trace"] for j in jobs)

    run_conformance(backend_name, scenario)


async def _post_cancel(backend, job_id: str):
    """POST /api/jobs/{id}/cancel the way a submitter would (raw HTTP —
    the cancel surface is part of the wire contract under test)."""
    async with aiohttp.ClientSession() as session:
        async with session.post(
                f"{backend.uri}/jobs/{job_id}/cancel",
                headers={"Authorization": f"Bearer {TOKEN}"}) as resp:
            return resp.status, await resp.json()


def test_cancel_queued_job_is_tombstoned(backend_name):
    """ISSUE 10: cancelling a QUEUED job answers 200 with
    {"id", "status": "cancelled", "cancelled": true}, the job is never
    handed out afterwards, and a repeat cancel is idempotent. Pinned
    across all three backends so fake_hive cannot drift."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-cancel-q"))
        status, payload = await _post_cancel(backend, "conf-cancel-q")
        assert status == 200
        assert payload["id"] == "conf-cancel-q"
        assert payload["status"] == "cancelled"
        assert payload["cancelled"] is True
        # tombstoned: the next poll hands nothing
        assert await client.ask_for_work(dict(CAPS)) == []
        # idempotent repeat
        status, payload = await _post_cancel(backend, "conf-cancel-q")
        assert status == 200 and payload["status"] == "cancelled"
        # unknown ids are a 404, not a silent no-op
        status, _ = await _post_cancel(backend, "conf-no-such-job")
        assert status == 404

    run_conformance(backend_name, scenario)


def test_cancel_leased_job_piggybacks_and_result_acks_cancelled(backend_name):
    """ISSUE 10, the mid-flight half of the wire contract: cancelling a
    LEASED job makes the lessee's next /work reply carry the id in a
    top-level `cancels` list (absent entirely when there is nothing to
    revoke — a legacy worker sees no new key), and a result arriving
    AFTER the cancel is ACKed 200 with the `cancelled` disposition so
    the worker's outbox parks instead of retrying forever."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-cancel-l"))
        [job] = await client.ask_for_work(dict(CAPS))
        assert job["id"] == "conf-cancel-l"
        assert client.last_cancels == []
        status, payload = await _post_cancel(backend, "conf-cancel-l")
        assert status == 200 and payload["cancelled"] is True
        # the revocation rides the next poll, once
        assert await client.ask_for_work(dict(CAPS)) == []
        assert client.last_cancels == ["conf-cancel-l"]
        assert await client.ask_for_work(dict(CAPS)) == []
        assert client.last_cancels == []
        # the late result earns the cancelled disposition, still a 200
        # ACK (at-least-once delivery must terminate, never 4xx-park as
        # a hive refusal)
        ack = await client.submit_result({
            "id": "conf-cancel-l", "artifacts": {}, "nsfw": False,
            "worker_version": "0.1.0", "pipeline_config": {}})
        assert ack["status"] == "ok"
        assert ack["cancelled"] is True

    run_conformance(backend_name, scenario)


def test_cancel_after_result_is_noop(backend_name):
    """The other side of the cancel-vs-result race: a job that already
    settled answers the cancel with cancelled=false and keeps its
    result — whichever settles first wins, pinned identically across
    backends."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-cancel-race"))
        [job] = await client.ask_for_work(dict(CAPS))
        await client.submit_result({
            "id": job["id"], "artifacts": {}, "nsfw": False,
            "worker_version": "0.1.0", "pipeline_config": {}})
        status, payload = await _post_cancel(backend, job["id"])
        assert status == 200
        assert payload["cancelled"] is False
        assert payload["status"] in ("done", "settling")

    run_conformance(backend_name, scenario)


def test_cancel_only_poll_never_dispatches(backend_name):
    """The saturated-worker heartbeat: a /work poll carrying
    `cancel_only=1` gets an empty jobs list even with work queued (and
    still hears revocations) — the wire shape every backend must share
    for mid-denoise cancellation to reach a busy worker."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-hb"))
        jobs = await client.ask_for_work(dict(CAPS, cancel_only=1))
        assert jobs == []
        # the job is still there for a normal poll
        jobs = await client.ask_for_work(dict(CAPS))
        assert [j["id"] for j in jobs] == ["conf-hb"]

    run_conformance(backend_name, scenario)


async def _post_job(backend, job: dict):
    """POST /api/jobs the way a submitter would (the coordinator's own
    submit surface, part of the wire contract since ISSUE 11 pinned the
    tenant field)."""
    async with aiohttp.ClientSession() as session:
        async with session.post(
                f"{backend.uri}/jobs", data=json.dumps(job),
                headers={"Authorization": f"Bearer {TOKEN}",
                         "Content-type": "application/json"}) as resp:
            return resp.status, await resp.json()


async def _get_json(backend, path: str):
    async with aiohttp.ClientSession() as session:
        async with session.get(
                f"{backend.uri}{path}",
                headers={"Authorization": f"Bearer {TOKEN}"}) as resp:
            return resp.status, await resp.json()


def test_submit_echoes_tenant(backend_name):
    """ISSUE 11: a submitted job's `tenant` field is accepted and echoed
    by both the submit ACK and GET /api/jobs/{id}; a job without one
    bills to the shared "anon" tenant. Pinned across all three backends
    so fake_hive cannot drift from the accounting wire contract."""

    async def scenario(backend, client):
        status, ack = await _post_job(
            backend, dict(echo_job("conf-tenant-1"), tenant="acme"))
        assert status == 200
        assert ack["id"] == "conf-tenant-1"
        assert ack["tenant"] == "acme"
        status, snapshot = await _get_json(backend, "/jobs/conf-tenant-1")
        assert status == 200
        assert snapshot["tenant"] == "acme"
        # tenant-less submissions land on the shared anonymous tenant
        status, ack = await _post_job(backend, echo_job("conf-tenant-2"))
        assert status == 200 and ack["tenant"] == "anon"
        status, snapshot = await _get_json(backend, "/jobs/conf-tenant-2")
        assert status == 200 and snapshot["tenant"] == "anon"

    run_conformance(backend_name, scenario)


def test_stats_poll_param_accepted(backend_name):
    """ISSUE 11: the compact per-stage EWMA blob workers piggyback on
    /work (`stats`, a JSON string) is accepted by every backend — jobs
    still flow — and a stats-aware hive parses it for its fleet view."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-stats"))
        blob = json.dumps({"a": 0.2, "s": {"job": [1.25, 9]}})
        jobs = await client.ask_for_work(dict(CAPS, stats=blob))
        assert [j["id"] for j in jobs] == ["conf-stats"]
        if backend.name == "fake":
            assert backend.hive.work_requests[-1]["stats"] == blob
        else:
            [worker] = backend.server.directory.live()
            assert worker.stats == {"job": (1.25, 9)}

    run_conformance(backend_name, scenario)


def test_shard_geometry_poll_params_accepted(backend_name):
    """ISSUE 12: the slice-geometry advertisement (`chips_per_slice`,
    `shard_capable`) is accepted by every backend — jobs still flow —
    and a geometry-aware hive parses it for its dispatch preference
    (interactive seeds prefer a shard-capable worker)."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-shard"))
        jobs = await client.ask_for_work(
            dict(CAPS, chips_per_slice=8, shard_capable=1))
        assert [j["id"] for j in jobs] == ["conf-shard"]
        if backend.name == "fake":
            recorded = backend.hive.work_requests[-1]
            assert recorded["chips_per_slice"] == "8"
            assert recorded["shard_capable"] == "1"
        else:
            [worker] = backend.server.directory.live()
            assert worker.chips_per_slice == 8
            assert worker.shard_capable is True
            assert worker.snapshot()["shard_capable"] is True

    run_conformance(backend_name, scenario)


def test_usage_reply_shape(backend_name):
    """ISSUE 11: GET /api/usage answers the pinned per-tenant ledger
    shape — a settled job's chip-seconds/rows land under its tenant and
    in the totals — and GET /api/tenants/{id}/usage filters to one
    tenant. Identical across fake/real/promoted backends."""

    USAGE_FIELDS = {"jobs", "chip_seconds", "rows", "coalesced_jobs",
                    "coalesce_saved_seconds", "embed_cache_hits",
                    "artifact_bytes", "operand_upload_bytes_saved",
                    "flops", "petaflops", "fallback_jobs"}

    async def scenario(backend, client):
        status, _ = await _post_job(
            backend, dict(echo_job("conf-usage"), tenant="acme"))
        assert status == 200
        [job] = await client.ask_for_work(dict(CAPS))
        await client.submit_result({
            "id": job["id"], "artifacts": {}, "nsfw": False,
            "worker_version": "0.1.0",
            "pipeline_config": {"timings": {"job_s": 1.5},
                                # serving-path cost stamp (ISSUE 17): the
                                # ledger bills the job's own integer FLOPs
                                "cost": {"flops": 2_000_000_000_000}}})
        status, usage = await _get_json(backend, "/usage")
        assert status == 200
        assert isinstance(usage["tenants"], dict)
        assert set(usage["tenants"]["acme"]) == USAGE_FIELDS
        assert usage["tenants"]["acme"]["jobs"] == 1
        assert usage["tenants"]["acme"]["chip_seconds"] == 1.5
        assert usage["tenants"]["acme"]["fallback_jobs"] == 0
        # FLOPs land integer-exact under the tenant AND in the totals,
        # with the human-scale petaflops twin derived from the same sum
        assert usage["tenants"]["acme"]["flops"] == 2_000_000_000_000
        assert usage["tenants"]["acme"]["petaflops"] == 0.002
        assert set(usage["totals"]) == USAGE_FIELDS
        assert usage["totals"]["jobs"] >= 1
        assert usage["totals"]["flops"] >= 2_000_000_000_000
        status, one = await _get_json(backend, "/tenants/acme/usage")
        assert status == 200
        assert one["tenant"] == "acme" and one["known"] is True
        assert set(one["usage"]) == USAGE_FIELDS
        status, none = await _get_json(backend, "/tenants/nobody/usage")
        assert status == 200
        assert none["known"] is False and none["usage"]["jobs"] == 0

    run_conformance(backend_name, scenario)


def test_slo_reply_shape(backend_name):
    """ISSUE 11: GET /api/slo answers the pinned engine-report shape on
    every backend — enabled flag, both window spans, and the per-class
    map (empty when no hive_slo is configured, as here)."""

    async def scenario(backend, client):
        status, report = await _get_json(backend, "/slo")
        assert status == 200
        assert report["enabled"] is False
        assert isinstance(report["classes"], dict)
        assert report["classes"] == {}
        assert report["fast_window_s"] > 0
        assert report["slow_window_s"] >= report["fast_window_s"]
        assert "fast_burn_degraded" in report

    run_conformance(backend_name, scenario)


def test_work_query_carries_placement_signal(backend_name):
    """Satellite: the /work poll itself carries the dispatcher's
    placement inputs — worker identity, chip capabilities, resident
    models, and local queue depth — with every value stringified."""

    async def scenario(backend, client):
        await client.ask_for_work(dict(CAPS, queue_depth=2))
        if backend.name == "fake":
            recorded = backend.hive.work_requests[-1]
        else:
            worker = backend.server.directory.live()[0]
            recorded = {
                "worker_name": worker.name,
                "worker_version": worker.version,
                "chips": str(worker.chips),
                "queue_depth": str(worker.queue_depth),
                "resident_models": ",".join(sorted(worker.resident)),
                "resident_adapters": ",".join(
                    sorted(worker.resident_adapters)),
            }
        assert recorded["worker_name"] == "worker"
        assert recorded["worker_version"]
        assert recorded["chips"] == "4"
        assert recorded["queue_depth"] == "2"
        # the client injects the registry's warm set when the caller
        # didn't provide one (empty registry here -> empty string)
        assert "resident_models" in recorded
        # ... and likewise the operand-residency set (ISSUE 16; empty
        # operand cache here -> empty string)
        assert "resident_adapters" in recorded

    run_conformance(backend_name, scenario)


def test_resident_adapters_drive_adapter_affinity(backend_name):
    """ISSUE 16: the /work poll advertises which adapters' stacked
    device operands are warm on the poller (`resident_adapters`), and a
    residency-aware hive places a model-warm job carrying one of those
    adapters as the `adapter_affinity` outcome — the zero-upload
    dispatch. Pinned across fake/real/promoted so fake_hive cannot
    drift from the operand-residency wire contract."""

    async def scenario(backend, client):
        from chiaswarm_tpu.hive_server.dispatch import _DISPATCH

        model = "stabilityai/stable-diffusion-2-1"
        backend.queue_job({
            "id": "conf-adapter-aff", "workflow": "txt2img",
            "model_name": model, "prompt": "warm operands",
            "height": 64, "width": 64, "num_inference_steps": 2,
            "lora": "style-a"})
        before = _DISPATCH.value(outcome="adapter_affinity")
        jobs = await client.ask_for_work(dict(
            CAPS, resident_models=model,
            resident_adapters="style-a,style-b"))
        assert [j["id"] for j in jobs] == ["conf-adapter-aff"]
        if backend.name == "fake":
            recorded = backend.hive.work_requests[-1]
            assert recorded["resident_adapters"] == "style-a,style-b"
        else:
            [worker] = backend.server.directory.live()
            assert worker.resident_adapters == {"style-a", "style-b"}
            assert worker.snapshot()["resident_adapters"] == [
                "style-a", "style-b"]
            # the dispatcher counted the zero-upload placement
            assert _DISPATCH.value(
                outcome="adapter_affinity") == before + 1

    run_conformance(backend_name, scenario)


async def _post_partial(backend, job_id: str, kind: str, payload: dict):
    """POST /api/jobs/{id}/checkpoint|preview raw (the refusal status
    codes are part of the wire contract under test; HiveClient's
    post_partial deliberately flattens them to None)."""
    async with aiohttp.ClientSession() as session:
        async with session.post(
                f"{backend.uri}/jobs/{job_id}/{kind}",
                data=json.dumps(payload),
                headers={"Authorization": f"Bearer {TOKEN}",
                         "Content-type": "application/json"}) as resp:
            return resp.status, await resp.json()


def _partial_payload(step: int, blob: bytes, **extra) -> dict:
    return {"step": step, "worker_name": "worker",
            "blob": base64.b64encode(blob).decode("ascii"), **extra}


def test_checkpoint_post_ack_and_refusals(backend_name):
    """ISSUE 18: the lessee's mid-pass checkpoint POST is ACKed
    {"status": "ok", "step", "sha256"} with the content digest of the
    blob it just durably stored; an unknown id is a 404, a body without
    a base64 `blob` is a 400, and a job that already settled answers
    409 {"message", "status"} — stale state can never shadow live
    state. Pinned across all three backends so fake_hive cannot
    drift."""

    async def scenario(backend, client):
        backend.queue_job(echo_job("conf-ckpt"))
        [job] = await client.ask_for_work(dict(CAPS))
        state = b"latents-at-step-12"
        status, ack = await _post_partial(
            backend, "conf-ckpt", "checkpoint",
            _partial_payload(12, state, signature="prog-sig"))
        assert status == 200
        assert ack["status"] == "ok"
        assert ack["step"] == 12
        assert ack["sha256"] == hashlib.sha256(state).hexdigest()
        # unknown ids are a 404, not a silent 200
        status, payload = await _post_partial(
            backend, "conf-no-such-job", "checkpoint",
            _partial_payload(1, b"x"))
        assert status == 404 and "message" in payload
        # a body without a base64 blob is a 400
        status, payload = await _post_partial(
            backend, "conf-ckpt", "checkpoint", {"step": 13})
        assert status == 400 and "message" in payload
        # once the result settles, further partials are refused with the
        # job's disposition (the worker's shipper stops, never retries)
        await client.submit_result({
            "id": "conf-ckpt", "artifacts": {}, "nsfw": False,
            "worker_version": "0.1.0", "pipeline_config": {}})
        status, payload = await _post_partial(
            backend, "conf-ckpt", "checkpoint",
            _partial_payload(14, b"too-late"))
        assert status == 409
        assert payload["status"] in ("done", "settling")
        assert "message" in payload

    run_conformance(backend_name, scenario)


def test_resume_offer_on_redelivery(backend_name):
    """ISSUE 18: a redelivered job whose previous lessee shipped a
    checkpoint carries a `resume` offer on the /work reply — exactly
    {href, step, signature} — for a resume-capable poller, and the href
    serves back the exact blob bytes through the worker's own client.
    The first delivery carries no offer (there is nothing to resume
    from). Pinned across all three backends so fake_hive cannot
    drift."""

    async def scenario(backend, client):
        job = echo_job("conf-resume")
        backend.queue_job(job)
        caps = dict(CAPS, resume_capable=1)
        [handed] = await client.ask_for_work(caps)
        assert "resume" not in handed  # attempt 1: nothing to resume
        state = b"ckpt-state-at-step-20"
        status, ack = await _post_partial(
            backend, "conf-resume", "checkpoint",
            _partial_payload(20, state, signature="prog-sig"))
        assert status == 200
        backend.redeliver(job)
        [again] = await client.ask_for_work(caps)
        assert again["id"] == "conf-resume"
        assert again["trace"]["attempt"] == 2
        offer = again["resume"]
        assert set(offer) == {"href", "step", "signature"}
        assert offer["href"] == f"/api/artifacts/{ack['sha256']}"
        assert offer["step"] == 20
        assert offer["signature"] == "prog-sig"
        # the offer's href serves the exact checkpoint bytes back
        # through the client call the worker's rehydration path uses
        assert await client.fetch_artifact(offer["href"]) == state

    run_conformance(backend_name, scenario)


def test_no_resume_offer_for_legacy_pollers(backend_name):
    """ISSUE 18: the resume offer is capability-gated — a poller that
    does not advertise `resume_capable` sees the pre-resume wire shape
    on a redelivery even when a checkpoint exists (it would have no way
    to rehydrate the blob)."""

    async def scenario(backend, client):
        job = echo_job("conf-legacy")
        backend.queue_job(job)
        [handed] = await client.ask_for_work(dict(CAPS))
        status, _ = await _post_partial(
            backend, "conf-legacy", "checkpoint",
            _partial_payload(8, b"ckpt", signature="sig"))
        assert status == 200
        backend.redeliver(job)
        [again] = await client.ask_for_work(dict(CAPS))
        assert again["id"] == "conf-legacy"
        assert "resume" not in again

    run_conformance(backend_name, scenario)


STAGE_CAPS = dict(CAPS, stages="encode,denoise,decode,postprocess")


def chain_workflow(workflow_id: str, n: int = 2, **extra) -> dict:
    """An explicit-chain workflow of echo stage-jobs (each mapping to
    the CPU-servable `postprocess` stage), the simplest graph every
    backend can run end to end."""
    return {"id": workflow_id,
            "stages": [{"workflow": "echo", "model_name": "none",
                        "prompt": f"stage {i}"} for i in range(n)],
            **extra}


async def _post_workflow(backend, payload: dict):
    """POST /api/workflows raw (the refusal status codes are part of
    the wire contract under test)."""
    async with aiohttp.ClientSession() as session:
        async with session.post(
                f"{backend.uri}/workflows", data=json.dumps(payload),
                headers={"Authorization": f"Bearer {TOKEN}",
                         "Content-type": "application/json"}) as resp:
            return resp.status, await resp.json()


def test_workflow_submit_ack_shape(backend_name):
    """ISSUE 20: POST /api/workflows ACKs the expanded graph — parent
    id, class/tenant attribution, running state, and one {stage, index,
    id, status} entry per stage with ready roots already `queued` and
    dependents `blocked`; resubmitting the same id is idempotent; an
    inexpandable submission is a 400 with a message. Pinned across all
    three backends so fake_hive cannot drift."""

    async def scenario(backend, client):
        ack = await client.submit_workflow(
            dict(chain_workflow("conf-wf-ack"), tenant="acme"))
        assert ack["id"] == "conf-wf-ack"
        assert ack["status"] == "running"
        assert ack["tenant"] == "acme"
        assert isinstance(ack["class"], str) and ack["class"]
        assert isinstance(ack["depth"], int)
        stages = ack["stages"]
        assert [s["index"] for s in stages] == [0, 1]
        assert all(set(s) == {"stage", "index", "id", "status"}
                   for s in stages)
        assert stages[0]["id"] == "conf-wf-ack-s0-postprocess"
        assert stages[0]["status"] == "queued"   # ready root admitted
        assert stages[1]["status"] == "blocked"  # awaits its need
        # idempotent resubmission: same graph, no duplicate stages
        again = await client.submit_workflow(chain_workflow("conf-wf-ack"))
        assert [s["id"] for s in again["stages"]] == [
            s["id"] for s in stages]
        # a workflow with no expansion is a 400 refusal, never a 500
        status, payload = await _post_workflow(
            backend, {"workflow": "txt2audio", "model_name": "m"})
        assert status == 400 and "message" in payload
        status, payload = await _post_workflow(backend, {"stages": []})
        assert status == 400 and "message" in payload

    run_conformance(backend_name, scenario)


def test_stage_job_wire_trace_carries_graph_coordinates(backend_name):
    """ISSUE 20: a dispatched stage-job's wire trace carries its graph
    coordinates — exactly {workflow_id, stage, index} under
    trace.stage — and the job itself carries the stage context with the
    parent id and, for successors, the predecessor's spool handoff as
    content-addressed input refs. A monolithic dispatch carries NO
    stage key anywhere. Pinned across all three backends."""

    async def scenario(backend, client):
        await client.submit_workflow(chain_workflow("conf-wf-tr"))
        [job] = await client.ask_for_work(dict(STAGE_CAPS))
        assert job["id"] == "conf-wf-tr-s0-postprocess"
        coords = job["trace"]["stage"]
        assert coords == {"workflow_id": "conf-wf-tr",
                          "stage": "postprocess", "index": 0}
        assert job["stage"]["workflow"] == "conf-wf-tr"
        assert job["stage"]["needs"] == []
        # settle stage 0: its successor admits with the handoff inputs
        await client.submit_result({
            "id": job["id"],
            "artifacts": {"primary": {"blob": "aGVsbG8=",
                                      "content_type": "image/jpeg"}},
            "nsfw": False, "worker_version": "0.1.0",
            "pipeline_config": {}})
        [nxt] = await client.ask_for_work(dict(STAGE_CAPS))
        assert nxt["id"] == "conf-wf-tr-s1-postprocess"
        assert nxt["trace"]["stage"]["index"] == 1
        [handoff] = nxt["stage"]["inputs"]
        assert handoff["stage"] == "postprocess" and handoff["index"] == 0
        ref = handoff["artifacts"]["primary"]
        assert "blob" not in ref  # refs travel, blobs stay spooled
        assert ref["sha256"] == hashlib.sha256(b"hello").hexdigest()
        assert ref["bytes"] == 5
        # the href rehydrates the exact bytes through the worker's own
        # artifact client — the spool handoff round-trips
        assert await client.fetch_artifact(ref["href"]) == b"hello"
        # a monolithic job's trace has no stage key at all
        backend.queue_job(echo_job("conf-mono-tr"))
        [mono] = await client.ask_for_work(dict(STAGE_CAPS))
        assert "stage" not in mono["trace"] and "stage" not in mono

    run_conformance(backend_name, scenario)


def test_workflow_parent_aggregation(backend_name):
    """ISSUE 20: GET /api/workflows/{id} aggregates the parent view —
    per-stage lifecycle with attempts and worker, the pooled usage
    totals across every stage-job, and (once done) the final stage's
    envelope as the workflow result; the /trace twin merges every
    stage's timeline with the settle->admit seam attributed as
    `stage_handoff`. Pinned across all three backends."""

    async def scenario(backend, client):
        await client.submit_workflow(
            dict(chain_workflow("conf-wf-agg"), tenant="acme"))
        for index in range(2):
            [job] = await client.ask_for_work(dict(STAGE_CAPS))
            assert job["id"] == f"conf-wf-agg-s{index}-postprocess"
            await client.submit_result({
                "id": job["id"],
                "artifacts": {"primary": {"blob": "aGVsbG8=",
                                          "content_type": "image/jpeg"}},
                "nsfw": False, "worker_version": "0.1.0",
                "pipeline_config": {"timings": {"job_s": 0.5}}})
        status, parent = await _get_json(backend, "/workflows/conf-wf-agg")
        assert status == 200
        assert parent["id"] == "conf-wf-agg"
        assert parent["status"] == "done"
        assert parent["tenant"] == "acme"
        for s in parent["stages"]:
            assert set(s) == {"stage", "index", "id", "status",
                              "attempts", "worker"}
            assert s["status"] == "done"
            assert s["attempts"] >= 1
            assert s["worker"] == "worker"
        # both stage-jobs pool under the parent's usage totals
        assert parent["usage"]["jobs"] == 2
        assert parent["usage"]["chip_seconds"] == 1.0
        # the final stage's spooled envelope IS the workflow result
        ref = parent["result"]["artifacts"]["primary"]
        assert ref["sha256"] == hashlib.sha256(b"hello").hexdigest()
        status, trace = await _get_json(
            backend, "/workflows/conf-wf-agg/trace")
        assert status == 200
        assert trace["workflow"] is True and trace["status"] == "done"
        assert trace["stage_states"] == {"postprocess": "done"}
        assert trace["open"] is False
        assert any(g["attribution"] == "stage_handoff"
                   for g in trace["gaps"])
        # unknown workflow ids are a 404, on both surfaces
        status, _ = await _get_json(backend, "/workflows/conf-nope")
        assert status == 404
        status, _ = await _get_json(backend, "/workflows/conf-nope/trace")
        assert status == 404

    run_conformance(backend_name, scenario)


def test_stage_jobs_opaque_to_legacy_pollers(backend_name):
    """ISSUE 20: stage-typed placement on the wire — a poller that does
    not advertise `stages` NEVER receives a stage-job (legacy opacity),
    a poller advertising the wrong stages waits too, chip-path stages
    (denoise) refuse chip-less hosts even when advertised, and a
    stage-aware poller still receives monolithic work unchanged.
    Pinned across all three backends so fake_hive cannot drift."""

    async def scenario(backend, client):
        await client.submit_workflow(chain_workflow("conf-wf-leg", n=1))
        # legacy poller: no `stages` param -> no graph work, ever
        assert await client.ask_for_work(dict(CAPS)) == []
        # wrong stage set advertised -> still withheld
        assert await client.ask_for_work(
            dict(CAPS, stages="encode,decode")) == []
        # chip stage on a chip-less host: a denoise stage-job is
        # withheld even from a poller advertising the stage
        await client.submit_workflow({
            "id": "conf-wf-chip",
            "stages": [gang_job(0)]})  # txt2img -> the denoise stage
        assert await client.ask_for_work(
            dict(CAPS, chips=0, stages="denoise")) == []
        # the right advertisement drains both
        jobs = await client.ask_for_work(dict(STAGE_CAPS))
        assert {j["id"] for j in jobs} == {
            "conf-wf-leg-s0-postprocess", "conf-wf-chip-s0-denoise"}
        # monolithic work still flows to a stage-aware poller
        backend.queue_job(echo_job("conf-mono-leg"))
        [mono] = await client.ask_for_work(dict(STAGE_CAPS))
        assert mono["id"] == "conf-mono-leg"

    run_conformance(backend_name, scenario)


def test_preview_partial_disposition(backend_name):
    """ISSUE 18: progressive previews surface on GET /api/jobs/{id} as
    the `partial` disposition — {"previews": [{"step", "href"}, ...],
    "checkpoint_step"?} — strictly while the pass is in flight; the
    preview href serves the decoded bytes; a checkpoint alone (no
    preview yet) surfaces nothing; and settling clears the disposition
    so a finished job never advertises stale partials. Pinned across
    all three backends so fake_hive cannot drift."""

    async def scenario(backend, client):
        status, _ = await _post_job(backend, echo_job("conf-preview"))
        assert status == 200
        [job] = await client.ask_for_work(dict(CAPS))
        # a checkpoint alone is resume state, not a tenant-visible
        # partial — the disposition appears only once a preview exists
        status, _ = await _post_partial(
            backend, "conf-preview", "checkpoint",
            _partial_payload(10, b"ckpt-state", signature="sig"))
        assert status == 200
        status, snapshot = await _get_json(backend, "/jobs/conf-preview")
        assert status == 200 and "partial" not in snapshot
        pixels = b"decoded-jpeg-bytes"
        status, ack = await _post_partial(
            backend, "conf-preview", "preview",
            _partial_payload(8, pixels, content_type="image/jpeg"))
        assert status == 200
        assert ack["status"] == "ok" and ack["step"] == 8
        assert ack["href"] == (
            f"/api/artifacts/{hashlib.sha256(pixels).hexdigest()}")
        status, snapshot = await _get_json(backend, "/jobs/conf-preview")
        assert status == 200
        partial = snapshot["partial"]
        assert partial["previews"] == [{"step": 8, "href": ack["href"]}]
        assert partial["checkpoint_step"] == 10
        assert await client.fetch_artifact(ack["href"]) == pixels
        # previews append in order
        status, ack2 = await _post_partial(
            backend, "conf-preview", "preview",
            _partial_payload(16, b"later-preview"))
        assert status == 200
        status, snapshot = await _get_json(backend, "/jobs/conf-preview")
        assert [p["step"] for p in snapshot["partial"]["previews"]] == [8, 16]
        # settle: the partial disposition disappears from the reply
        await client.submit_result({
            "id": "conf-preview", "artifacts": {}, "nsfw": False,
            "worker_version": "0.1.0", "pipeline_config": {}})
        status, snapshot = await _get_json(backend, "/jobs/conf-preview")
        assert status == 200 and "partial" not in snapshot

    run_conformance(backend_name, scenario)
