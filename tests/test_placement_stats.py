"""tools/placement_stats.py contract tests: the placement summary on
synthetic exposition text, and the REAL in-process claim smoke — so the
operator's view of the dispatch board can't rot between TPU windows."""

import importlib.util
import pathlib
import sys

_TOOLS = pathlib.Path(__file__).resolve().parent.parent / "tools"


def _load_tool():
    # placement_stats imports the exposition parser from metrics_dump
    if "metrics_dump" not in sys.modules:
        md_spec = importlib.util.spec_from_file_location(
            "metrics_dump", _TOOLS / "metrics_dump.py")
        md = importlib.util.module_from_spec(md_spec)
        sys.modules["metrics_dump"] = md
        md_spec.loader.exec_module(md)
    spec = importlib.util.spec_from_file_location(
        "placement_stats", _TOOLS / "placement_stats.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("placement_stats", mod)
    spec.loader.exec_module(mod)
    return mod


SYNTHETIC = """\
# TYPE swarm_placement_total counter
swarm_placement_total{outcome="affinity"} 6
swarm_placement_total{outcome="steal"} 2
swarm_placement_total{outcome="cold"} 2
# TYPE swarm_batch_flush_total counter
swarm_batch_flush_total{reason="linger"} 5
swarm_batch_flush_total{reason="preempt"} 1
"""


def test_placement_summary_from_synthetic_text():
    tool = _load_tool()
    summary = tool.placement_summary(tool.parse_metrics(SYNTHETIC))
    assert summary["placements"] == {"affinity": 6, "steal": 2, "cold": 2}
    assert summary["claimed"] == 10
    assert summary["affinity_hit_rate"] == 0.6
    assert summary["steals"] == 2
    assert summary["flushes"]["preempt"] == 1

    table = tool.render(summary)
    assert "affinity_hit_rate: 0.6" in table
    assert "preempt" in table

    # empty input degrades to a message, not a crash
    empty = tool.placement_summary([])
    assert empty["affinity_hit_rate"] is None
    assert "no placements" in tool.render(empty)


def test_inprocess_claim_smoke_prints_placement_table(sdaas_root, capsys):
    """The tool's no-worker mode drives the real dispatch-board claim
    path (cold -> affinity -> steal) and prints nonzero placements."""
    tool = _load_tool()
    rc = tool.main([])
    out = capsys.readouterr().out
    assert rc == 0
    assert "cold -> affinity -> steal" in out.replace("claim sequence: ", "") \
        or "affinity" in out
    assert "affinity_hit_rate" in out
    assert "steals" in out
