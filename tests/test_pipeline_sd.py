"""End-to-end SD pipeline tests on tiny configs (hermetic, CPU mesh).

The reference's only 'test' was eyeballing real-GPU output (SURVEY §4);
here the full job path — registry residency, text encode, scan denoise with
CFG, VAE decode, PIL artifacts — runs on random tiny weights in seconds.
"""

import numpy as np
import pytest
from PIL import Image

from chiaswarm_tpu import registry
from chiaswarm_tpu.chips.device import ChipSet
from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

import jax


@pytest.fixture(autouse=True)
def clean_registry():
    registry.clear_cache()
    yield
    registry.clear_cache()


@pytest.fixture(scope="module")
def tiny_sd():
    return SDPipeline("test/tiny-sd")


@pytest.fixture(scope="module")
def tiny_xl():
    return SDPipeline("test/tiny-xl")


def test_txt2img_basic(tiny_sd):
    images, config = tiny_sd.run(
        prompt="a photo of a cat",
        height=64,
        width=64,
        num_inference_steps=3,
        rng=jax.random.key(7),
    )
    assert len(images) == 1
    assert images[0].size == (64, 64)
    assert config["mode"] == "txt2img"
    assert config["steps"] == 3
    assert config["timings"]["denoise_decode_s"] > 0


def test_txt2img_deterministic_given_seed(tiny_sd):
    run = lambda: np.asarray(
        tiny_sd.run(
            prompt="same seed",
            height=64,
            width=64,
            num_inference_steps=2,
            rng=jax.random.key(3),
        )[0][0]
    )
    np.testing.assert_array_equal(run(), run())


def test_txt2img_seed_changes_output(tiny_sd):
    a = np.asarray(
        tiny_sd.run(prompt="x", height=64, width=64, num_inference_steps=2,
                    rng=jax.random.key(1))[0][0]
    )
    b = np.asarray(
        tiny_sd.run(prompt="x", height=64, width=64, num_inference_steps=2,
                    rng=jax.random.key(2))[0][0]
    )
    assert not np.array_equal(a, b)


@pytest.mark.parametrize(
    "scheduler",
    ["EulerDiscreteScheduler", "EulerAncestralDiscreteScheduler",
     "DDIMScheduler", "LCMScheduler", "HeunDiscreteScheduler",
     "UniPCMultistepScheduler"],
)
def test_scheduler_variants(tiny_sd, scheduler):
    images, config = tiny_sd.run(
        prompt="scheduler test", height=64, width=64, num_inference_steps=2,
        scheduler_type=scheduler, rng=jax.random.key(0),
    )
    arr = np.asarray(images[0])
    assert arr.shape == (64, 64, 3)
    assert config["scheduler"] == scheduler


def test_img2img(tiny_sd):
    start = Image.fromarray(
        (np.random.default_rng(0).random((64, 64, 3)) * 255).astype(np.uint8)
    )
    images, config = tiny_sd.run(
        prompt="repaint", image=start, strength=0.5, num_inference_steps=4,
        rng=jax.random.key(0),
    )
    assert config["mode"] == "img2img"
    assert images[0].size == (64, 64)


def test_inpaint_without_init_image_is_job_error(tiny_sd):
    mask = Image.fromarray(np.full((64, 64), 255, np.uint8))
    with pytest.raises(ValueError, match="inpaint requires an init image"):
        tiny_sd.run(prompt="fill", mask_image=mask, num_inference_steps=2,
                    rng=jax.random.key(0))


def test_inpaint_preserves_unmasked_region(tiny_sd):
    rng = np.random.default_rng(1)
    start = Image.fromarray((rng.random((64, 64, 3)) * 255).astype(np.uint8))
    # repaint only the left half
    mask = np.zeros((64, 64), np.uint8)
    mask[:, :32] = 255
    images, config = tiny_sd.run(
        prompt="fill", image=start, mask_image=Image.fromarray(mask),
        strength=1.0, num_inference_steps=3, rng=jax.random.key(0),
    )
    assert config["mode"] == "inpaint"
    out = np.asarray(images[0], np.float32)
    # The unmasked (right) half rides the original's noise trajectory, so it
    # is nearly seed-independent; the masked half is sampled. Exact equality
    # is impossible — the VAE decoder's global attention bleeds masked
    # content everywhere — so assert the contrast, not bit-equality.
    out2 = np.asarray(
        tiny_sd.run(
            prompt="fill", image=start, mask_image=Image.fromarray(mask),
            strength=1.0, num_inference_steps=3, rng=jax.random.key(9),
        )[0][0],
        np.float32,
    )
    right_diff = np.abs(out[:, 32:] - out2[:, 32:]).mean()
    left_diff = np.abs(out[:, :32] - out2[:, :32]).mean()
    assert left_diff > 4 * right_diff, (left_diff, right_diff)


def test_sdxl_branch(tiny_xl):
    images, config = tiny_xl.run(
        prompt="xl", height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert images[0].size == (64, 64)
    assert tiny_xl.is_xl


def test_batch_sharded_over_mesh():
    chipset = ChipSet(jax.devices())  # all 8 virtual devices, 'data' axis
    pipe = SDPipeline("test/tiny-sd-mesh", chipset=chipset)
    assert pipe.data_parts == 8
    images, _ = pipe.run(
        prompt="sharded", height=64, width=64, num_inference_steps=2,
        num_images_per_prompt=8, rng=jax.random.key(0),
    )
    assert len(images) == 8


def test_registry_residency():
    p1 = registry.get_pipeline("test/tiny-sd", "StableDiffusionPipeline")
    p2 = registry.get_pipeline("test/tiny-sd", "StableDiffusionImg2ImgPipeline")
    assert p1 is p2  # same family + model -> one resident bundle


def test_program_cache_reused(tiny_sd):
    # clear BOTH cache levels: the assembled-runner cache memoizes the
    # whole execution strategy, so a warm runner never re-resolves
    # programs — clearing only _programs would assert against a pass
    # that (correctly) compiled nothing
    tiny_sd._programs.clear()
    tiny_sd._runner_cache.clear()
    kw = dict(prompt="warm", height=64, width=64, num_inference_steps=2,
              rng=jax.random.key(0))
    tiny_sd.run(**kw)
    assert len(tiny_sd._programs) == 1
    tiny_sd.run(**kw)
    assert len(tiny_sd._programs) == 1  # same bucket -> no retrace
    tiny_sd.run(prompt="warm", height=128, width=64, num_inference_steps=2,
                rng=jax.random.key(0))
    assert len(tiny_sd._programs) == 2


def test_prediction_type_from_scheduler_config(sdaas_root, tmp_path):
    # a renamed v-prediction checkpoint must still get v_prediction when the
    # downloaded scheduler config says so (name heuristic alone says epsilon)
    import json

    from chiaswarm_tpu.pipelines.stable_diffusion import _family_configs
    from chiaswarm_tpu.settings import Settings, save_settings

    model_root = tmp_path / "models"
    name = "acme/stable-diffusion-2-renamed"
    sched = model_root / name / "scheduler"
    sched.mkdir(parents=True)
    (sched / "scheduler_config.json").write_text(
        json.dumps({"prediction_type": "v_prediction"})
    )
    save_settings(Settings(model_root_dir=str(model_root)))
    assert _family_configs(name)[4] == "v_prediction"
    # and the heuristic still stands when no local config exists
    assert _family_configs("acme/stable-diffusion-2-other")[4] == "epsilon"


def test_upscale_falls_back_when_upscaler_weights_missing(
    monkeypatch, sdaas_root
):
    # ADVICE r2: upscale jobs must not die on MissingWeightsError when the
    # learned sd-x2 upscaler isn't converted — latent-resize 2x serves them
    from chiaswarm_tpu.pipelines import upscale as upscale_mod

    monkeypatch.setattr(
        upscale_mod, "upscaler_name_for",
        lambda name: "stabilityai/sd-x2-latent-upscaler",
    )
    pipe = SDPipeline("test/tiny-sd")
    images, config = pipe.run(
        prompt="x", height=64, width=64, num_inference_steps=2,
        upscale=True, rng=jax.random.key(0),
    )
    assert images[0].size == (128, 128)
    assert config["upscaled"] is True
    assert config["upscaler"] == "latent-resize-fallback"
    assert config["output_size"] == [128, 128]


# --- coalesced img2img (run_batched "batched_i2i" variant, ISSUE 4) ---


def _start_image(color):
    return Image.new("RGB", (64, 64), color)


def test_run_batched_img2img_stacked_init_latents(tiny_sd):
    """Two independent img2img requests with DIFFERENT start images share
    one padded pass: per-request envelopes, per-row init latents (each
    request's output depends on its own start image), and determinism
    given the same rngs."""
    requests = [
        {"prompt": "repaint red", "rng": jax.random.key(1),
         "image": _start_image((255, 0, 0))},
        {"prompt": "repaint blue", "rng": jax.random.key(2),
         "num_images_per_prompt": 2, "image": _start_image((0, 0, 255))},
    ]
    results = tiny_sd.run_batched(
        [dict(r) for r in requests], num_inference_steps=4, strength=0.5,
        scheduler_type="EulerDiscreteScheduler",
    )
    assert len(results) == 2
    (imgs_a, cfg_a), (imgs_b, cfg_b) = results
    assert len(imgs_a) == 1 and len(imgs_b) == 2
    assert imgs_a[0].size == (64, 64)
    for cfg in (cfg_a, cfg_b):
        assert cfg["mode"] == "img2img"
        assert cfg["strength"] == 0.5
        assert cfg["batched_with"] == 2
        assert cfg["padded_rows"] == 4  # 3 real rows pad to the pow2 bucket

    # same rngs + same start images -> identical pixels (row independence
    # means request A's rows can't be perturbed by B's)
    rerun = tiny_sd.run_batched(
        [dict(r) for r in requests], num_inference_steps=4, strength=0.5,
        scheduler_type="EulerDiscreteScheduler",
    )
    assert np.array_equal(np.asarray(imgs_a[0]), np.asarray(rerun[0][0][0]))

    # a different start image for A changes A's output
    swapped = [dict(requests[0], image=_start_image((0, 255, 0))),
               dict(requests[1])]
    moved = tiny_sd.run_batched(
        swapped, num_inference_steps=4, strength=0.5,
        scheduler_type="EulerDiscreteScheduler",
    )
    assert not np.array_equal(np.asarray(imgs_a[0]), np.asarray(moved[0][0][0]))


def test_run_batched_img2img_rejects_mixed_groups(tiny_sd):
    with pytest.raises(ValueError, match="missing a start image"):
        tiny_sd.run_batched(
            [{"prompt": "has image", "rng": jax.random.key(1),
              "image": _start_image((10, 10, 10))},
             {"prompt": "no image", "rng": jax.random.key(2)}],
            num_inference_steps=2,
        )
    # differently-sized start images: the solo path sizes each job's
    # canvas to ITS image, which one shared program can't reproduce —
    # raise so the worker's per-job fallback serves exact solo semantics
    with pytest.raises(ValueError, match="mixed start-image sizes"):
        tiny_sd.run_batched(
            [{"prompt": "small", "rng": jax.random.key(1),
              "image": _start_image((10, 10, 10))},
             {"prompt": "large", "rng": jax.random.key(2),
              "image": Image.new("RGB", (128, 128), (20, 20, 20))}],
            num_inference_steps=2,
        )
