"""Hive replication & failover (ISSUE 7): the WAL event stream, the
standby's tail/resume semantics, promotion, and split-brain fencing.

Covers the journal's replication-sequence protocol (incremental tail vs
reset-after-compaction), a standby replicating a live primary over real
HTTP while refusing work itself, stream resume across a torn WAL tail
and across compaction (retired history never replayed), promotion
semantics (fresh lease deadlines, epoch bump, durable across a restart
of the promoted hive), stale-epoch fencing, the drop_replication fault
point, and the health-check-driven auto-failover loop.
"""

import asyncio
import dataclasses
import json

import aiohttp
import pytest

from chiaswarm_tpu import faults
from chiaswarm_tpu.hive_server import HiveServer, StandbyHive
from chiaswarm_tpu.hive_server.journal import (
    HiveJournal,
    ev_admit,
    ev_epoch,
    snapshot_events,
)
from chiaswarm_tpu.settings import Settings

TOKEN = "replication-test-token"


@pytest.fixture(autouse=True)
def disarm_faults():
    yield
    faults.configure("")


def _settings(**overrides) -> Settings:
    fields = dict(sdaas_token=TOKEN, hive_port=0, metrics_port=0,
                  hive_wal_dir="wal_primary")
    fields.update(overrides)
    return Settings(**fields)


def _standby_settings(primary: Settings, **overrides) -> Settings:
    return dataclasses.replace(primary, hive_wal_dir="wal_standby",
                               **overrides)


def _echo(job_id: str) -> dict:
    return {"id": job_id, "workflow": "echo", "model_name": "none",
            "prompt": job_id}


def _headers(**extra) -> dict:
    headers = {"Authorization": f"Bearer {TOKEN}",
               "Content-type": "application/json"}
    headers.update(extra)
    return headers


async def _submit(session, server, job: dict) -> str:
    async with session.post(f"{server.api_uri}/jobs", data=json.dumps(job),
                            headers=_headers()) as r:
        assert r.status == 200, await r.text()
        return (await r.json())["id"]


async def _poll(session, server, name: str, **extra):
    params = {"worker_version": "0.1.0", "worker_name": name}
    params.update({k: str(v) for k, v in extra.items()})
    async with session.get(f"{server.api_uri}/work", params=params,
                           headers=_headers()) as r:
        payload = None
        try:
            payload = await r.json()
        except aiohttp.ContentTypeError:
            pass
        return r.status, payload


# --- journal stream protocol (no sockets) -----------------------------------


def test_stream_since_incremental_and_reset(tmp_path):
    journal = HiveJournal(tmp_path / "wal")
    for i in range(4):
        journal.append(ev_admit(type("R", (), {
            "job": {"id": f"j{i}"}, "job_class": "default", "seq": i,
            "submitted_wall": 0.0, "attempts": 0})()))
    assert journal.last_rs == 4

    events, reset = journal.stream_since(0)
    assert not reset and [e["rs"] for e in events] == [1, 2, 3, 4]
    events, reset = journal.stream_since(2)
    assert not reset and [e["rs"] for e in events] == [3, 4]
    events, reset = journal.stream_since(4)
    assert not reset and events == []

    # compaction re-stamps fresh sequences: a standby AT the old tip is
    # still continuous (idempotent snapshot re-apply), one behind is not
    snapshot = [ev_admit(type("R", (), {
        "job": {"id": "j3"}, "job_class": "default", "seq": 3,
        "submitted_wall": 0.0, "attempts": 0})())]
    journal.compact(snapshot)
    assert journal.stream_start_rs == 5
    events, reset = journal.stream_since(4)
    assert not reset and [e["rs"] for e in events] == [5]
    events, reset = journal.stream_since(2)
    assert reset and [e["rs"] for e in events] == [5]
    journal.close()


def test_stream_since_ahead_of_counter_forces_reset(tmp_path):
    """A standby position AHEAD of the journal's counter (primary lost
    WAL tail to power loss, or was stood up over a wiped dir) must be a
    reset — an empty incremental reply would leave the standby silently
    filtering every future event as already-seen."""
    journal = HiveJournal(tmp_path / "wal")
    journal.append(ev_epoch(1))
    assert journal.last_rs == 1
    events, reset = journal.stream_since(50)
    assert reset
    assert [e["rs"] for e in events] == [1]
    journal.close()


def test_epoch_event_survives_recover_and_snapshot(tmp_path):
    journal = HiveJournal(tmp_path / "wal")
    journal.append(ev_epoch(3))
    journal.close()
    reopened = HiveJournal(tmp_path / "wal")
    events = reopened.recover()
    assert events[0]["ev"] == "epoch" and events[0]["epoch"] == 3
    reopened.close()
    # snapshot_events leads with the epoch so replay sees it first
    from chiaswarm_tpu.hive_server.leases import LeaseTable
    from chiaswarm_tpu.hive_server.queue import PriorityJobQueue

    events = snapshot_events(PriorityJobQueue(), LeaseTable(10, 1), epoch=2)
    assert events[0] == {"ev": "epoch", "epoch": 2}


# --- standby replication over HTTP ------------------------------------------


def test_standby_replicates_and_refuses_until_promoted(sdaas_root):
    async def scenario():
        primary_settings = _settings()
        primary = await HiveServer(primary_settings, port=0).start()
        standby = StandbyHive(_standby_settings(primary_settings),
                              primary_uri=primary.uri, port=0)
        await standby.server.start()
        async with aiohttp.ClientSession() as session:
            for i in range(3):
                await _submit(session, primary, _echo(f"rep-{i}"))
            status, payload = await _poll(session, primary, "w1")
            assert status == 200
            assert [j["id"] for j in payload["jobs"]] == ["rep-0"]

            await standby.sync_once()
            states = {k: v.state
                      for k, v in standby.server.queue.records.items()}
            assert states == {"rep-0": "leased", "rep-1": "queued",
                              "rep-2": "queued"}
            # replicated queue preserves dispatch order
            assert [r.job_id
                    for r in standby.server.queue.iter_queued()] == \
                ["rep-1", "rep-2"]

            # a standby must not dispatch, settle, or admit
            status, payload = await _poll(session, standby.server, "w2")
            assert status == 409
            assert payload["message"].startswith("not primary")
            async with session.post(
                    f"{standby.server.api_uri}/results",
                    data=json.dumps({"id": "rep-0", "artifacts": {}}),
                    headers=_headers()) as r:
                assert r.status == 409
            async with session.post(
                    f"{standby.server.api_uri}/jobs",
                    data=json.dumps(_echo("rep-x")),
                    headers=_headers()) as r:
                assert r.status == 409
            # reads stay open on a standby (ops visibility)
            async with session.get(
                    f"{standby.server.api_uri}/jobs/rep-1",
                    headers=_headers()) as r:
                assert r.status == 200

            health = standby.server.health()
            assert health["role"] == "standby"
        await primary.stop()
        await standby.stop()

    asyncio.run(scenario())


def test_stream_resumes_after_primary_restart_with_torn_tail(sdaas_root):
    """A crash mid-append leaves a torn tail in the primary's WAL; the
    restarted primary skips it, and the standby resumes the stream and
    converges — the torn transition resolves like any lost event."""

    async def scenario():
        primary_settings = _settings()
        primary = await HiveServer(primary_settings, port=0).start()
        port = primary.port
        standby = StandbyHive(_standby_settings(primary_settings),
                              primary_uri=primary.uri, port=0)
        await standby.server.start()
        async with aiohttp.ClientSession() as session:
            for i in range(2):
                await _submit(session, primary, _echo(f"torn-{i}"))
            await standby.sync_once()
            assert len(standby.server.queue.records) == 2
            wal_path = primary.journal.path
            await primary.stop()
            # the crash interrupted an append: half a JSON line on disk
            with open(wal_path, "ab") as fh:
                fh.write(b'{"ev": "admit", "job": {"id": "torn-lost')

            restarted = await HiveServer(primary_settings, port=port).start()
            assert restarted.journal.torn_lines == 1
            await _submit(session, restarted, _echo("torn-2"))
            await standby.sync_once()
            assert set(standby.server.queue.records) == \
                {"torn-0", "torn-1", "torn-2"}
            assert "torn-lost" not in standby.server.queue.records
            await restarted.stop()
        await standby.stop()

    asyncio.run(scenario())


def test_stream_resets_across_compaction_without_retired_history(sdaas_root):
    """A standby whose position was compacted away full-resyncs from the
    snapshot: pruned (retired) jobs never reach it, and its state lands
    exactly on the primary's."""

    async def scenario():
        # history_limit=1 so settling jobs retires older finished records
        primary_settings = _settings(hive_job_history_limit=1)
        primary = await HiveServer(primary_settings, port=0).start()
        standby = StandbyHive(_standby_settings(primary_settings),
                              primary_uri=primary.uri, port=0)
        await standby.server.start()
        async with aiohttp.ClientSession() as session:
            for i in range(3):
                await _submit(session, primary, _echo(f"cmp-{i}"))
            await standby.sync_once()
            before_reset_position = standby.since
            assert before_reset_position > 0

            # the primary settles two jobs (the older retires under the
            # history limit) and compacts — the standby's position is gone
            for i in range(2):
                status, payload = await _poll(session, primary, "w1")
                job_id = payload["jobs"][0]["id"]
                async with session.post(
                        f"{primary.api_uri}/results",
                        data=json.dumps({"id": job_id, "artifacts": {}}),
                        headers=_headers()) as r:
                    assert r.status == 200
            assert "cmp-0" not in primary.queue.records  # retired
            primary.journal.compact(primary.journal.snapshot_fn())

            applied = await standby.sync_once()
            assert applied > 0
            assert set(standby.server.queue.records) == \
                set(primary.queue.records)
            assert standby.server.queue.records["cmp-1"].state == "done"
            assert "cmp-0" not in standby.server.queue.records
            assert standby.since > before_reset_position
        await primary.stop()
        await standby.stop()

    asyncio.run(scenario())


def test_drop_replication_fault_then_clean_resume(sdaas_root):
    async def scenario():
        primary_settings = _settings()
        primary = await HiveServer(primary_settings, port=0).start()
        standby = StandbyHive(_standby_settings(primary_settings),
                              primary_uri=primary.uri, port=0)
        await standby.server.start()
        async with aiohttp.ClientSession() as session:
            await _submit(session, primary, _echo("fault-0"))
        faults.configure("drop_replication=1")
        with pytest.raises(faults.FaultInjected):
            await standby.sync_once()
        assert standby.server.queue.records == {}
        # the next sync resumes from the same position, nothing doubled
        await standby.sync_once()
        assert set(standby.server.queue.records) == {"fault-0"}
        assert faults.get_plan().fired("drop_replication") == 1
        await primary.stop()
        await standby.stop()

    asyncio.run(scenario())


# --- promotion + fencing ----------------------------------------------------


def test_promote_bumps_epoch_regrants_leases_and_persists(sdaas_root):
    async def scenario():
        primary_settings = _settings(hive_lease_deadline_s=50.0)
        primary = await HiveServer(primary_settings, port=0).start()
        standby_settings = _standby_settings(
            primary_settings, hive_lease_deadline_s=50.0)
        standby = StandbyHive(standby_settings,
                              primary_uri=primary.uri, port=0)
        await standby.server.start()
        async with aiohttp.ClientSession() as session:
            await _submit(session, primary, _echo("pro-0"))
            await _submit(session, primary, _echo("pro-1"))
            status, payload = await _poll(session, primary, "doomed")
            assert [j["id"] for j in payload["jobs"]] == ["pro-0"]
            await standby.sync_once()
            await primary.stop()

            promoted = await standby.promote()
            assert standby.promoted
            assert promoted.epoch == 1
            assert promoted.standby is False
            # the replicated lease was re-granted with a FRESH deadline
            lease = promoted.leases.get("pro-0")
            assert lease is not None and lease.worker == "doomed"
            remaining = lease.expires_at - promoted.leases.clock.mono()
            assert remaining == pytest.approx(50.0, abs=5.0)

            # the promoted hive serves: dispatch + settle work now
            status, payload = await _poll(session, standby.server, "w2")
            assert status == 200
            assert [j["id"] for j in payload["jobs"]] == ["pro-1"]
            assert standby.server.health()["role"] == "primary"
        await standby.stop()

        # promotion is DURABLE: a restart of the promoted hive keeps the
        # epoch and the record table (its own WAL got the snapshot)
        restarted = HiveServer(standby_settings, port=0)
        assert restarted.epoch == 1
        assert set(restarted.queue.records) == {"pro-0", "pro-1"}
        if restarted.journal is not None:
            restarted.journal.close()

    asyncio.run(scenario())


def test_stale_epoch_requests_fenced_with_409(sdaas_root):
    async def scenario():
        primary = await HiveServer(_settings(), port=0).start()
        async with aiohttp.ClientSession() as session:
            await _submit(session, primary, _echo("fence-0"))
            # an epoch-5 worker polling an epoch-0 hive: deposed, refuse
            params = {"worker_version": "0.1.0", "worker_name": "w"}
            async with session.get(
                    f"{primary.api_uri}/work", params=params,
                    headers=_headers(**{"X-Hive-Epoch": "5"})) as r:
                assert r.status == 409
                assert "stale hive epoch" in (await r.json())["message"]
            async with session.post(
                    f"{primary.api_uri}/results",
                    data=json.dumps({"id": "fence-0", "artifacts": {}}),
                    headers=_headers(**{"X-Hive-Epoch": "5"})) as r:
                assert r.status == 409
            assert primary.queue.records["fence-0"].state == "queued"
            # the same requests without the newer epoch are served
            async with session.get(
                    f"{primary.api_uri}/work", params=params,
                    headers=_headers()) as r:
                assert r.status == 200
                assert r.headers["X-Hive-Epoch"] == "0"
        await primary.stop()

    asyncio.run(scenario())


def test_health_check_loop_promotes_after_grace(sdaas_root):
    """The autonomous path: primary dies, the replication loop's health
    checks fail past hive_failover_grace_s, the standby promotes itself."""

    async def scenario():
        primary_settings = _settings()
        primary = await HiveServer(primary_settings, port=0).start()
        standby = await StandbyHive(
            _standby_settings(primary_settings,
                              hive_replication_poll_s=0.05,
                              hive_failover_grace_s=0.3),
            primary_uri=primary.uri, port=0).start()
        async with aiohttp.ClientSession() as session:
            await _submit(session, primary, _echo("auto-0"))
        deadline = asyncio.get_running_loop().time() + 10.0
        while not standby.server.queue.records:
            assert asyncio.get_running_loop().time() < deadline, \
                "standby never caught up"
            await asyncio.sleep(0.02)
        await primary.stop()
        deadline = asyncio.get_running_loop().time() + 20.0
        while not standby.promoted:
            assert asyncio.get_running_loop().time() < deadline, \
                "standby never promoted itself"
            await asyncio.sleep(0.02)
        assert standby.server.epoch == 1
        assert set(standby.server.queue.records) == {"auto-0"}
        await standby.stop()

    asyncio.run(scenario())


def test_replication_stream_requires_wal(sdaas_root):
    async def scenario():
        primary = await HiveServer(_settings(hive_wal_dir=""), port=0).start()
        async with aiohttp.ClientSession() as session:
            async with session.get(
                    f"{primary.api_uri}/replication/stream",
                    params={"since": "0"}, headers=_headers()) as r:
                assert r.status == 400
                assert "hive_wal_dir" in (await r.json())["message"]
        await primary.stop()

    asyncio.run(scenario())


def test_standby_healthz_reports_replication_lag_and_degrades(sdaas_root):
    """ISSUE 8 satellite: a standby's /healthz carries the replication
    view (applied rs vs the primary's stream tip + seconds since the
    last applied sync) and goes degraded (503) once the stream stalls
    past hive_replication_lag_degraded_s — a silently stalled standby
    must be visible BEFORE a failover discovers it is hopelessly
    behind."""

    async def scenario():
        base = _settings()
        primary = await HiveServer(base, port=0).start()
        standby = StandbyHive(
            _standby_settings(base, hive_replication_lag_degraded_s=0.2),
            primary_uri=primary.uri, port=0)
        await standby.server.start()
        try:
            async with aiohttp.ClientSession() as session:
                await _submit(session, primary, _echo("lag-1"))
                await standby.sync_once()
                async with session.get(f"{standby.server.uri}/healthz",
                                       headers=_headers()) as r:
                    assert r.status == 200
                    health = await r.json()
                rep = health["replication"]
                assert rep["promoted"] is False
                assert rep["rs_applied"] >= 1
                assert rep["rs_delta"] == 0
                assert rep["last_sync_age_s"] is not None

                # the primary goes dark; past the threshold the standby
                # reports itself degraded with the stall named
                await primary.stop()
                await asyncio.sleep(0.3)
                async with session.get(f"{standby.server.uri}/healthz",
                                       headers=_headers()) as r:
                    assert r.status == 503
                    health = await r.json()
                assert any("replication stalled" in reason
                           for reason in health["degraded_reasons"])

                # promotion clears the verdict: a primary is not lagging
                await standby.promote()
                async with session.get(f"{standby.server.uri}/healthz",
                                       headers=_headers()) as r:
                    assert r.status == 200
        finally:
            await standby.stop()

    asyncio.run(scenario())
