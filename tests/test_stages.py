"""Chained-stage tests: latent 2x upscale, SDXL refiner pass."""

import numpy as np
import pytest

import jax

from chiaswarm_tpu import registry
from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline


@pytest.fixture(autouse=True)
def clean_registry():
    registry.clear_cache()
    yield
    registry.clear_cache()


def test_upscale_doubles_output_size():
    pipe = SDPipeline("test/tiny-sd")
    images, config = pipe.run(
        prompt="upscaled", height=64, width=64, num_inference_steps=2,
        upscale=True, rng=jax.random.key(0),
    )
    assert images[0].size == (128, 128)
    assert config["size"] == [64, 64]  # canvas pre-upscale, reference parity


def test_refiner_stage_chains():
    pipe = SDPipeline("test/tiny-xl")
    images, config = pipe.run(
        prompt="refined", height=64, width=64, num_inference_steps=2,
        refiner={"model_name": "test/tiny-xl-refiner"},
        rng=jax.random.key(0),
    )
    assert len(images) == 1
    assert images[0].size == (64, 64)
    assert "refiner_s" in config["timings"]
    # refiner became resident in the registry for subsequent jobs
    assert any("tiny-xl-refiner" in str(k) for k in registry._CACHE.keys())
