"""MLSD / LineArt learned-annotator conversion (VERDICT r03 next #3).

The checkpoint side is the torch mirrors in torch_unet_ref.py (exact
upstream key layouts): random torch init with non-trivial BatchNorm
running stats -> state dict -> convert -> flax forward must equal the
torch eval forward. The preprocessor wiring is proven by dropping a
converted .pth into the model root and asserting the real detector
serves (and the degraded flag clears).
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(__file__))

torch = pytest.importorskip("torch")

from torch_unet_ref import LineartGeneratorT, MLSDLargeT  # noqa: E402

from chiaswarm_tpu.models.conversion import (  # noqa: E402
    convert_lineart,
    convert_mlsd,
)
from chiaswarm_tpu.models.lineart import LineartGenerator  # noqa: E402
from chiaswarm_tpu.models.mlsd import MLSDNet  # noqa: E402


def _randomize_bn_stats(module, seed):
    """Non-trivial running stats so the folding math is actually
    exercised (fresh BNs have mean 0 / var 1, which folding can fake)."""
    g = torch.Generator().manual_seed(seed)
    for m in module.modules():
        if isinstance(m, torch.nn.BatchNorm2d):
            m.running_mean.copy_(torch.randn(m.num_features, generator=g) * 0.2)
            m.running_var.copy_(
                torch.rand(m.num_features, generator=g) * 1.5 + 0.3
            )


def _state_numpy(module) -> dict:
    return {k: v.detach().numpy() for k, v in module.state_dict().items()}


def test_mlsd_torch_parity():
    torch.manual_seed(60)
    mirror = MLSDLargeT()
    with torch.no_grad():
        _randomize_bn_stats(mirror, 61)
    mirror.eval()
    params = convert_mlsd(_state_numpy(mirror))

    rng = np.random.default_rng(62)
    x = rng.standard_normal((1, 64, 64, 4)).astype(np.float32)
    with torch.no_grad():
        out_t = mirror(
            torch.from_numpy(x).permute(0, 3, 1, 2)
        ).permute(0, 2, 3, 1).numpy()
    out_f = MLSDNet().apply({"params": params}, jnp.asarray(x))
    assert out_f.shape == out_t.shape  # [1, 32, 32, 9]
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=3e-4, rtol=1e-3)


def test_mlsd_accepts_dataparallel_prefix():
    torch.manual_seed(63)
    mirror = MLSDLargeT()
    mirror.eval()
    state = {f"module.{k}": v for k, v in _state_numpy(mirror).items()}
    params = convert_mlsd(state)
    assert "features_0" in params and "block23" in params


def test_lineart_torch_parity():
    torch.manual_seed(64)
    mirror = LineartGeneratorT(base=8, n_res=2)
    mirror.eval()
    cfg, params = convert_lineart(_state_numpy(mirror))
    assert cfg.base_channels == 8 and cfg.n_residual_blocks == 2

    rng = np.random.default_rng(65)
    x = rng.random((2, 32, 32, 3)).astype(np.float32)
    with torch.no_grad():
        out_t = mirror(
            torch.from_numpy(x).permute(0, 3, 1, 2)
        ).permute(0, 2, 3, 1).numpy()
    out_f = LineartGenerator(cfg).apply({"params": params}, jnp.asarray(x))
    assert out_f.shape == out_t.shape  # transposed convs restore H, W
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=3e-4, rtol=1e-3)


def test_annotator_preprocessors_serve_real_weights(sdaas_root, tmp_path):
    """Converted .pth files under the model root flip mlsd/lineart from
    classical stand-ins to the real detectors, and the degraded flag
    clears (the envelope-visible contract from round 4's
    degraded_preprocessors work)."""
    from PIL import Image

    from chiaswarm_tpu.pipelines import aux_models
    from chiaswarm_tpu.pre_processors.controlnet import (
        is_degraded_preprocessor,
        preprocess_image,
    )
    from chiaswarm_tpu.settings import Settings, save_settings

    root = tmp_path / "models"
    annot = root / "lllyasviel/Annotators"
    annot.mkdir(parents=True)
    save_settings(Settings(model_root_dir=str(root)))

    torch.manual_seed(66)
    torch.save(MLSDLargeT().state_dict(),
               str(annot / "mlsd_large_512_fp32.pth"))
    torch.save(LineartGeneratorT(base=8, n_res=1).state_dict(),
               str(annot / "sk_model.pth"))

    aux_models._MLSD.clear()
    aux_models._LINEART.clear()
    try:
        assert aux_models.get_mlsd_detector() is not None
        assert aux_models.get_lineart_detector() is not None
        assert not is_degraded_preprocessor("mlsd")
        assert not is_degraded_preprocessor("lineart")

        img = Image.fromarray(
            (np.random.default_rng(67).random((96, 96, 3)) * 255).astype(
                np.uint8
            )
        )
        for name in ("mlsd", "lineart"):
            out = preprocess_image(img, name, "cpu")
            assert out.size == img.size
    finally:
        aux_models._MLSD.clear()
        aux_models._LINEART.clear()


def test_pidinet_torch_parity():
    """convert_pidinet's re-parameterization vs the functional pixel-
    difference ops (for 'cd', genuinely independent math)."""
    from torch_unet_ref import PiDiNetT

    from chiaswarm_tpu.models.conversion import convert_pidinet
    from chiaswarm_tpu.models.pidinet import PiDiNet

    torch.manual_seed(70)
    mirror = PiDiNetT()
    mirror.eval()
    params = convert_pidinet(_state_numpy(mirror))

    rng = np.random.default_rng(71)
    x = rng.random((1, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        out_t = mirror(
            torch.from_numpy(x).permute(0, 3, 1, 2)
        ).permute(0, 2, 3, 1).numpy()
    out_f = PiDiNet().apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=3e-4, rtol=1e-3)


def test_pidinet_preprocessor_serves_real_weights(sdaas_root, tmp_path):
    """A wrapped {'state_dict': module.-prefixed} table5_pidinet.pth (the
    published checkpoint's exact shape) serves the real soft_edge path."""
    from PIL import Image
    from torch_unet_ref import PiDiNetT

    from chiaswarm_tpu.pipelines import aux_models
    from chiaswarm_tpu.pre_processors.controlnet import preprocess_image
    from chiaswarm_tpu.settings import Settings, save_settings

    root = tmp_path / "models"
    annot = root / "lllyasviel/Annotators"
    annot.mkdir(parents=True)
    save_settings(Settings(model_root_dir=str(root)))

    torch.manual_seed(72)
    wrapped = {
        "state_dict": {
            f"module.{k}": v for k, v in PiDiNetT().state_dict().items()
        }
    }
    torch.save(wrapped, str(annot / "table5_pidinet.pth"))

    aux_models._PIDI.clear()
    try:
        assert aux_models.get_pidinet_detector() is not None
        img = Image.fromarray(
            (np.random.default_rng(73).random((80, 80, 3)) * 255).astype(
                np.uint8
            )
        )
        out = preprocess_image(img, "softedge", "cpu")
        assert out.size == img.size
    finally:
        aux_models._PIDI.clear()
