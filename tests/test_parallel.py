"""Mesh/sharding/ring-attention tests on the 8-virtual-device CPU backend."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from chiaswarm_tpu.parallel import (
    make_mesh,
    pad_batch,
    ring_self_attention_sharded,
    shard_batch,
)
from chiaswarm_tpu.parallel.tensor import partition_spec_tree, shard_params
from chiaswarm_tpu.ops.attention import reference_attention
from jax.sharding import PartitionSpec as P


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape == {"data": 8, "tensor": 1, "seq": 1}
    mesh = make_mesh(data=2, tensor=2, seq=2)
    assert mesh.shape == {"data": 2, "tensor": 2, "seq": 2}
    with pytest.raises(ValueError):
        make_mesh(data=3, tensor=2)


def test_pad_and_shard_batch():
    mesh = make_mesh(data=4, tensor=2)
    assert pad_batch(3, 4) == 4
    x = np.ones((4, 8, 8, 3), np.float32)
    placed = shard_batch(mesh, {"x": x, "s": np.float32(2.0)})
    assert placed["x"].sharding.spec == P("data", None, None, None)
    np.testing.assert_array_equal(np.asarray(placed["x"]), x)


@pytest.mark.parametrize("seq_devices", [2, 4, 8])
def test_ring_attention_matches_full(seq_devices):
    mesh = make_mesh(data=8 // seq_devices, seq=seq_devices)
    # move seq axis adjacency into the mesh: use only the seq submesh
    b, s, h, d = 2, 64, 4, 16
    rng = np.random.default_rng(0)
    q, k, v = (rng.standard_normal((b, s, h, d)).astype(np.float32) for _ in range(3))

    expected = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    got = ring_self_attention_sharded(mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)


def test_ring_attention_bf16():
    mesh = make_mesh(data=2, seq=4)
    b, s, h, d = 1, 32, 2, 8
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16) for _ in range(3)
    )
    expected = reference_attention(q, k, v)
    got = ring_self_attention_sharded(mesh, q, k, v)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(expected, np.float32), atol=3e-2
    )


def test_tensor_partition_rules_shard_attention_kernels():
    params = {
        "attn": {"to_q": {"kernel": np.zeros((32, 32), np.float32)},
                 "to_out_0": {"kernel": np.zeros((32, 32), np.float32),
                              "bias": np.zeros((32,), np.float32)}},
        "conv_in": {"kernel": np.zeros((3, 3, 4, 32), np.float32)},
    }
    specs = partition_spec_tree(params)
    assert specs["attn"]["to_q"]["kernel"] == P(None, "tensor")
    assert specs["attn"]["to_out_0"]["kernel"] == P("tensor", None)
    assert specs["attn"]["to_out_0"]["bias"] == P()
    assert specs["conv_in"]["kernel"] == P()

    mesh = make_mesh(data=4, tensor=2)
    placed = shard_params(mesh, params)
    assert placed["attn"]["to_q"]["kernel"].sharding.spec == P(None, "tensor")


def test_tensor_parallel_matmul_matches_dense():
    """Column->row parallel pair under pjit == dense matmul (psum inserted by XLA)."""
    mesh = make_mesh(data=1, tensor=8)
    rng = np.random.default_rng(2)
    x = rng.standard_normal((4, 64)).astype(np.float32)
    w1 = rng.standard_normal((64, 128)).astype(np.float32)
    w2 = rng.standard_normal((128, 64)).astype(np.float32)

    from jax.sharding import NamedSharding

    xw = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P()))
    w1s = jax.device_put(jnp.asarray(w1), NamedSharding(mesh, P(None, "tensor")))
    w2s = jax.device_put(jnp.asarray(w2), NamedSharding(mesh, P("tensor", None)))

    out = jax.jit(lambda x, a, b: jax.nn.relu(x @ a) @ b)(xw, w1s, w2s)
    expected = np.maximum(x @ w1, 0) @ w2
    np.testing.assert_allclose(np.asarray(out), expected, rtol=2e-4, atol=2e-4)


def test_sd_pipeline_tensor_parallel_matches_replicated():
    """THE serving-path TP check (VERDICT weak #4): the same job on a
    data+tensor ChipSet mesh must match the single-chip replicated run —
    same random weights (seeded by model name), same seed, sharded kernels.
    """
    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    chipset = ChipSet(jax.devices(), tensor=2)  # data=4, tensor=2
    tp = SDPipeline("test/tiny-sd", chipset=chipset)
    assert tp.tensor_parts == 2 and tp.data_parts == 4
    # UNet attention kernels actually sharded, not replicated
    spec = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(
            lambda x: x.sharding.spec,
            tp.params["unet"],
            is_leaf=lambda x: hasattr(x, "sharding"),
        )
    )
    assert any(s == P(None, "tensor") for s in spec)

    ref = SDPipeline("test/tiny-sd")
    kw = dict(prompt="tp parity", height=64, width=64, num_inference_steps=2,
              num_images_per_prompt=4)
    a = np.asarray(tp.run(rng=jax.random.key(11), **kw)[0][0], np.int32)
    b = np.asarray(ref.run(rng=jax.random.key(11), **kw)[0][0], np.int32)
    # fp32 CPU: sharded matmul + psum reassociates float sums; after uint8
    # quantization the outputs agree to the last-bit rounding boundary
    assert np.abs(a - b).max() <= 2, np.abs(a - b).max()
