"""LoRA merge tests: math, name-mapping (diffusers + kohya), job wiring."""

import numpy as np
import pytest
from safetensors.numpy import save_file

import jax
import jax.numpy as jnp

from chiaswarm_tpu.models.lora import collect_lora_deltas, merge_lora
from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

TARGET = "down_blocks_0/attentions_0/transformer_blocks_0/attn1/to_q"


def _params_with_kernel(shape=(32, 32)):
    kernel = np.ones(shape, np.float32)
    tree = {}
    node = tree
    for seg in TARGET.split("/")[:-1]:
        node = node.setdefault(seg, {})
    node[TARGET.split("/")[-1]] = {"kernel": jnp.asarray(kernel)}
    return tree


def _lora_state(name_style: str, rank=4, dim=32, alpha=None):
    rng = np.random.default_rng(0)
    a = rng.standard_normal((rank, dim)).astype(np.float32)  # [r, in]
    b = rng.standard_normal((dim, rank)).astype(np.float32)  # [out, r]
    if name_style == "diffusers":
        base = "unet." + TARGET.replace("/", ".")
        state = {f"{base}.lora_A.weight": a, f"{base}.lora_B.weight": b}
    else:
        base = "lora_unet_" + TARGET.replace("/", "_")
        state = {f"{base}.lora_down.weight": a, f"{base}.lora_up.weight": b}
        if alpha is not None:
            state[f"{base}.alpha"] = np.float32(alpha)
    return state, a, b


@pytest.mark.parametrize("style", ["diffusers", "kohya"])
def test_merge_math(style):
    params = _params_with_kernel()
    state, a, b = _lora_state(style)
    merged, matched = merge_lora(params, state, scale=0.5)
    assert matched == 1
    node = merged
    for seg in TARGET.split("/"):
        node = node[seg]
    expected = np.ones((32, 32), np.float32) + 0.5 * (b @ a).T
    np.testing.assert_allclose(np.asarray(node["kernel"]), expected, rtol=1e-6)
    # base tree untouched
    node0 = params
    for seg in TARGET.split("/"):
        node0 = node0[seg]
    np.testing.assert_array_equal(np.asarray(node0["kernel"]), 1.0)


def test_alpha_scaling():
    params = _params_with_kernel()
    state, a, b = _lora_state("kohya", rank=4, alpha=2.0)
    merged, matched = merge_lora(params, state, scale=1.0)
    node = merged
    for seg in TARGET.split("/"):
        node = node[seg]
    expected = np.ones((32, 32), np.float32) + (2.0 / 4.0) * (b @ a).T
    np.testing.assert_allclose(np.asarray(node["kernel"]), expected, rtol=1e-6)


def test_unmatched_modules_skipped():
    params = _params_with_kernel()
    state = {
        "unet.nonexistent.to_q.lora_A.weight": np.zeros((4, 32), np.float32),
        "unet.nonexistent.to_q.lora_B.weight": np.zeros((32, 4), np.float32),
    }
    _, matched = merge_lora(params, state, 1.0)
    assert matched == 0
    assert collect_lora_deltas(state)


def test_job_with_lora_changes_output(tmp_path):
    from chiaswarm_tpu import lora_cache

    pipe = SDPipeline("test/tiny-sd")
    q_kernel = np.asarray(
        pipe.params["unet"]["down_blocks_0"]["attentions_0"][
            "transformer_blocks_0"]["attn1"]["to_q"]["kernel"]
    )
    dim = q_kernel.shape[0]
    state, _, _ = _lora_state("diffusers", rank=2, dim=dim)
    lora_file = tmp_path / "adapter.safetensors"
    save_file(state, str(lora_file))

    lora_cache.configure(64 * 1024 * 1024)
    try:
        kw = dict(prompt="with lora", height=64, width=64,
                  num_inference_steps=2, rng=jax.random.key(4))
        base = np.asarray(pipe.run(**kw)[0][0])
        images, cfg = pipe.run(
            lora={"lora": str(lora_file)}, lora_scale=1.0, **kw)
        lored = np.asarray(images[0])
        assert not np.array_equal(base, lored)
        # ISSUE 13 serving path: runtime per-row delta on the resident
        # base tree — NO merged param-tree copy, factors cached once
        assert cfg["lora_mode"] == "delta"
        assert len(pipe._lora_cache) == 0
        assert len(lora_cache.get_cache()) == 1
        pipe.run(lora={"lora": str(lora_file)}, lora_scale=1.0, **kw)
        assert len(lora_cache.get_cache()) == 1
    finally:
        lora_cache.reset()


def test_merged_fallback_when_runtime_delta_disabled(tmp_path, monkeypatch):
    from chiaswarm_tpu import lora_cache

    pipe = SDPipeline("test/tiny-sd")
    q_kernel = np.asarray(
        pipe.params["unet"]["down_blocks_0"]["attentions_0"][
            "transformer_blocks_0"]["attn1"]["to_q"]["kernel"]
    )
    state, _, _ = _lora_state("diffusers", rank=2, dim=q_kernel.shape[0])
    lora_file = tmp_path / "adapter.safetensors"
    save_file(state, str(lora_file))

    monkeypatch.setenv("CHIASWARM_LORA_RUNTIME_DELTA", "0")
    lora_cache.configure(64 * 1024 * 1024)
    try:
        kw = dict(prompt="with lora", height=64, width=64,
                  num_inference_steps=2, rng=jax.random.key(4))
        images, cfg = pipe.run(
            lora={"lora": str(lora_file)}, lora_scale=1.0, **kw)
        assert cfg["lora_mode"] == "merged"
        # the merged tree is cached (tiny LRU) and reused
        assert len(pipe._lora_cache) == 1
        pipe.run(lora={"lora": str(lora_file)}, lora_scale=1.0, **kw)
        assert len(pipe._lora_cache) == 1
    finally:
        lora_cache.reset()


def test_missing_lora_is_fatal_value_error():
    pipe = SDPipeline("test/tiny-sd")
    with pytest.raises(ValueError, match="Could not load lora"):
        pipe.run(prompt="x", height=64, width=64, num_inference_steps=2,
                 lora={"lora": "/does/not/exist.safetensors"},
                 rng=jax.random.key(0))
