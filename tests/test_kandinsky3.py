"""Kandinsky 3 (SURVEY §2.7): single-stage T5-conditioned latent
diffusion, plus the AutoPipeline wire-name resolution the reference hive
uses for this family (swarm/test.py:130-147 sends
AutoPipelineForText2Image with a kandinsky-3 model name).
"""

import numpy as np
import pytest

import jax

from chiaswarm_tpu import registry
from chiaswarm_tpu.pipelines.kandinsky import KandinskyPipeline
from chiaswarm_tpu.pipelines.kandinsky3 import Kandinsky3Pipeline
from chiaswarm_tpu.weights import MissingWeightsError


@pytest.fixture(scope="module")
def tiny_k3():
    return Kandinsky3Pipeline("test/tiny-kandinsky3")


def test_txt2img(tiny_k3):
    images, config = tiny_k3.run(
        prompt="a fantasy landscape", height=64, width=64,
        num_inference_steps=2, rng=jax.random.key(0),
    )
    assert images[0].size == (64, 64)
    assert config["mode"] == "txt2img"
    assert config["timings"]["denoise_decode_s"] > 0


def test_prompt_conditions_output(tiny_k3):
    kw = dict(height=64, width=64, num_inference_steps=2,
              rng=jax.random.key(4))
    a = np.asarray(tiny_k3.run(prompt="a red fox", **kw)[0][0])
    b = np.asarray(tiny_k3.run(prompt="a blue whale", **kw)[0][0])
    assert not np.array_equal(a, b)


def test_deterministic(tiny_k3):
    kw = dict(prompt="same", height=64, width=64, num_inference_steps=2,
              rng=jax.random.key(7))
    np.testing.assert_array_equal(
        np.asarray(tiny_k3.run(**kw)[0][0]), np.asarray(tiny_k3.run(**kw)[0][0])
    )


def test_auto_pipeline_resolves_by_model_name():
    # the reference hive sends Kandinsky jobs as AutoPipelineForText2Image;
    # a type-only lookup would land them on the SD family
    k3 = registry.get_pipeline(
        "test/tiny-kandinsky3", "AutoPipelineForText2Image"
    )
    assert isinstance(k3, Kandinsky3Pipeline)
    k2 = registry.get_pipeline(
        "test/tiny-kandinsky", "AutoPipelineForText2Image"
    )
    assert isinstance(k2, KandinskyPipeline)
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    sd = registry.get_pipeline("test/tiny-sd", "DiffusionPipeline")
    assert isinstance(sd, SDPipeline)


def test_real_weights_fail_loud():
    with pytest.raises(MissingWeightsError):
        Kandinsky3Pipeline("kandinsky-community/kandinsky-3")


def test_img2img_conditions_on_image(tiny_k3):
    from PIL import Image as PILImage

    rng = np.random.default_rng(0)
    img = PILImage.fromarray(
        (rng.random((64, 64, 3)) * 255).astype(np.uint8)
    )
    img2 = PILImage.fromarray(
        (rng.random((64, 64, 3)) * 255).astype(np.uint8)
    )
    kw = dict(prompt="repaint", num_inference_steps=4, rng=jax.random.key(2))
    a, cfg = tiny_k3.run(image=img, strength=0.3, **kw)
    assert cfg["mode"] == "img2img"
    # the init image conditions the result (random weights preclude a
    # reconstruction-distance assertion; identity of inputs is testable)
    b, _ = tiny_k3.run(image=img2, strength=0.3, **kw)
    assert not np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
    # strength moves the start point
    c, _ = tiny_k3.run(image=img, strength=0.9, **kw)
    assert not np.array_equal(np.asarray(a[0]), np.asarray(c[0]))
