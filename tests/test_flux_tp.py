"""Flux multi-chip serving readiness (VERDICT r03 item 4).

Three claims, each previously asserted only in prose:
1. The TP-sharded Flux forward on an 8-device mesh computes EXACTLY what
   the single-device forward computes, with CONVERTED weights (diffusers
   layout -> convert_flux) — not just with random trees.
2. The requirements math is fact-based: FAMILY_PARAMS_GB["flux"] matches
   the parameter bytes of the real flux-dev geometry (measured via
   eval_shape, no materialization), and min_chips derives a >=2-chip TP
   requirement for a 16 GB v5e chip.
3. A 1-chip slice REFUSES flux jobs with the tensor-degree fix named, and
   the worker's capability advertisement carries flux_min_chips so a
   capability-aware hive never sends un-runnable flux jobs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chiaswarm_tpu.models.flux import TINY_FLUX, FluxTransformer

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from test_flux import _flux_flax_to_diffusers  # noqa: E402


def _tiny_inputs():
    rng = np.random.default_rng(7)
    b, s_img, s_txt = 2, 16, 8
    img = rng.standard_normal((b, s_img, TINY_FLUX.in_channels)).astype(
        np.float32
    )
    img_ids = np.zeros((b, s_img, 3), np.int32)
    img_ids[:, :, 1] = np.arange(s_img)[None] // 4
    img_ids[:, :, 2] = np.arange(s_img)[None] % 4
    txt = rng.standard_normal((b, s_txt, TINY_FLUX.context_dim)).astype(
        np.float32
    )
    txt_ids = np.zeros((b, s_txt, 3), np.int32)
    t = np.array([0.3, 0.9], np.float32)
    pooled = rng.standard_normal((b, TINY_FLUX.pooled_dim)).astype(np.float32)
    guidance = np.array([3.5, 3.5], np.float32)
    return img, img_ids, txt, txt_ids, t, pooled, guidance


def test_tp_forward_matches_single_with_converted_weights():
    from chiaswarm_tpu.models.conversion import convert_flux
    from chiaswarm_tpu.parallel.mesh import make_mesh
    from chiaswarm_tpu.parallel.tensor import shard_params

    model = FluxTransformer(TINY_FLUX)
    img, img_ids, txt, txt_ids, t, pooled, guidance = _tiny_inputs()
    ref = model.init(
        jax.random.key(1), jnp.asarray(img), jnp.asarray(img_ids),
        jnp.asarray(txt), jnp.asarray(txt_ids), jnp.asarray(t),
        jnp.asarray(pooled), guidance=jnp.asarray(guidance),
    )["params"]
    ref = jax.tree_util.tree_map(lambda x: np.asarray(x, np.float32), dict(ref))
    converted = convert_flux(_flux_flax_to_diffusers(ref))

    args = (
        jnp.asarray(img), jnp.asarray(img_ids), jnp.asarray(txt),
        jnp.asarray(txt_ids), jnp.asarray(t), jnp.asarray(pooled),
    )
    out_single = np.asarray(
        model.apply({"params": converted}, *args,
                    guidance=jnp.asarray(guidance))
    )

    assert len(jax.devices()) >= 8, "conftest provides 8 virtual devices"
    mesh = make_mesh(jax.devices()[:8], tensor=4)
    assert mesh.shape["tensor"] == 4 and mesh.shape["data"] == 2
    sharded = shard_params(mesh, converted)

    @jax.jit
    def run(p, *a):
        return model.apply({"params": p}, *a,
                           guidance=jnp.asarray(guidance))

    with mesh:
        out_tp = np.asarray(run(sharded, *args))
    np.testing.assert_allclose(out_tp, out_single, atol=2e-4, rtol=1e-3)


def test_flux_params_gb_is_fact_based():
    """The capacity table's flux footprint must match the real flux-dev
    geometry (bf16 bytes), measured without materializing anything."""
    from chiaswarm_tpu.chips.requirements import FAMILY_PARAMS_GB
    from chiaswarm_tpu.pipelines.flux import _flux_configs

    flux_cfg, t5_cfg, clip_cfg, vae_cfg, _, _, _ = _flux_configs(
        "black-forest-labs/FLUX.1-dev"
    )
    from chiaswarm_tpu.models.clip import CLIPTextEncoder
    from chiaswarm_tpu.models.flux import FluxTransformer
    from chiaswarm_tpu.models.t5 import T5Encoder
    from chiaswarm_tpu.models.vae import AutoencoderKL

    def count(module, *args, **kwargs):
        import functools

        fn = (functools.partial(module.init, **kwargs) if kwargs
              else module.init)
        shapes = jax.eval_shape(fn, jax.random.key(0), *args)["params"]
        return sum(
            int(np.prod(x.shape))
            for x in jax.tree_util.tree_leaves(shapes)
        )

    n = count(
        FluxTransformer(flux_cfg),
        jnp.zeros((1, 4, flux_cfg.in_channels)),
        jnp.zeros((1, 4, 3), jnp.int32),
        jnp.zeros((1, 8, flux_cfg.context_dim)),
        jnp.zeros((1, 8, 3), jnp.int32),
        jnp.zeros((1,)),
        jnp.zeros((1, flux_cfg.pooled_dim)),
        guidance=jnp.ones((1,)),
    )
    n += count(T5Encoder(t5_cfg), jnp.zeros((1, 8), jnp.int32))
    n += count(CLIPTextEncoder(clip_cfg), jnp.zeros((1, 77), jnp.int32))
    n += count(AutoencoderKL(vae_cfg), jnp.zeros((1, 32, 32, 3)))
    measured_gb = n * 2 / (1 << 30)  # bf16
    table_gb = FAMILY_PARAMS_GB["flux"]
    assert abs(measured_gb - table_gb) / table_gb < 0.2, (
        f"requirements table says {table_gb} GB, geometry measures "
        f"{measured_gb:.1f} GB"
    )


def test_one_chip_refuses_flux_naming_the_fix(monkeypatch, sdaas_root):
    """With weight streaming DISABLED (the round-4 contract), a 1-chip
    slice still refuses flux naming the tensor-degree fix; with streaming
    on (the default) the same slice is admitted — test_flux_stream.py."""
    from chiaswarm_tpu.chips.requirements import check_capacity, min_chips

    assert min_chips("black-forest-labs/FLUX.1-dev", 16.0) >= 2

    class FakeChip:
        platform = "tpu"
        tensor = 1
        seq = 1

        def hbm_bytes(self):
            return 16 << 30

        def chip_count(self):
            return 1

    assert check_capacity(
        FakeChip(), "black-forest-labs/FLUX.1-dev", 1, 1024) == 1

    monkeypatch.setenv("SDAAS_FLUX_STREAMING", "0")
    with pytest.raises(ValueError) as e:
        check_capacity(FakeChip(), "black-forest-labs/FLUX.1-dev", 1, 1024)
    assert "tensor" in str(e.value)


def test_capability_advertises_flux_min_chips(sdaas_root):
    """The worker tells the hive how many chips flux needs on THIS
    hardware, so a capability-aware hive can place (or skip) accordingly."""
    import asyncio

    from chiaswarm_tpu.chips.allocator import SliceAllocator
    from chiaswarm_tpu.settings import Settings
    from chiaswarm_tpu.worker import Worker

    w = Worker(
        settings=Settings(sdaas_token="t", worker_name="w"),
        allocator=SliceAllocator(chips_per_job=4),
        hive_uri="http://127.0.0.1:1/api",
    )
    caps = w._capabilities()
    # CPU slices are exempt from the HBM gate (fit_batch), so the
    # advertisement says runnable — matching what check_capacity admits;
    # flux_min_chips only appears on TPU slices where HBM math is real
    assert caps["flux_runnable"] == 1
    assert "flux_min_chips" not in caps
    assert "unconverted_families" in caps
    asyncio.run(w.hive.close())
