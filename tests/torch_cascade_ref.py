"""Exact-key torch mirror of the diffusers Stable Cascade graphs
(StableCascadeUNet + PaellaVQModel decode path), used to prove the flax
modules + conversion numerically (the same in-repo-reference strategy as
torch_unet_ref.py; diffusers itself is not available in this image).

State-dict keys match diffusers exactly so `convert_cascade_unet` /
`convert_paella_vq` exercise the real layouts: flattened per-level block
lists (`down_blocks.{level}.{idx}.*`), `.blocks.{m}` switch-level
scalers, biased attention projections under `attention.to_*`, ConvTranspose
up-scalers, and the Paella `depthwise.1` replication-padded convs.
"""

import math

import torch
import torch.nn as nn
import torch.nn.functional as F


class LayerNorm2dT(nn.LayerNorm):
    """SDCascadeLayerNorm: channel-last LN applied to NCHW maps."""

    def forward(self, x):
        x = x.permute(0, 2, 3, 1)
        x = super().forward(x)
        return x.permute(0, 3, 1, 2)


class GlobalResponseNormT(nn.Module):
    def __init__(self, dim):
        super().__init__()
        self.gamma = nn.Parameter(torch.zeros(1, 1, 1, dim))
        self.beta = nn.Parameter(torch.zeros(1, 1, 1, dim))

    def forward(self, x):  # NHWC
        agg = torch.norm(x, p=2, dim=(1, 2), keepdim=True)
        stand = agg / (agg.mean(dim=-1, keepdim=True) + 1e-6)
        return self.gamma * (x * stand) + self.beta + x


class ResBlockT(nn.Module):
    def __init__(self, c, c_skip=0, kernel_size=3):
        super().__init__()
        self.depthwise = nn.Conv2d(
            c, c, kernel_size=kernel_size, padding=kernel_size // 2, groups=c
        )
        self.norm = LayerNorm2dT(c, elementwise_affine=False, eps=1e-6)
        self.channelwise = nn.Sequential(
            nn.Linear(c + c_skip, c * 4),
            nn.GELU(),
            GlobalResponseNormT(c * 4),
            nn.Dropout(0.0),
            nn.Linear(c * 4, c),
        )

    def forward(self, x, x_skip=None):
        res = x
        x = self.norm(self.depthwise(x))
        if x_skip is not None:
            x = torch.cat([x, x_skip], dim=1)
        x = self.channelwise(x.permute(0, 2, 3, 1)).permute(0, 3, 1, 2)
        return x + res


class TimestepBlockT(nn.Module):
    def __init__(self, c, c_timestep, conds=()):
        super().__init__()
        self.mapper = nn.Linear(c_timestep, c * 2)
        self.conds = conds
        for cname in conds:
            setattr(self, f"mapper_{cname}", nn.Linear(c_timestep, c * 2))

    def forward(self, x, t):
        t = t.chunk(len(self.conds) + 1, dim=1)
        a, b = self.mapper(t[0])[:, :, None, None].chunk(2, dim=1)
        for i, cname in enumerate(self.conds):
            ac, bc = getattr(self, f"mapper_{cname}")(t[i + 1])[
                :, :, None, None
            ].chunk(2, dim=1)
            a, b = a + ac, b + bc
        return x * (1 + a) + b


class AttentionT(nn.Module):
    """diffusers Attention(bias=True) key layout: to_q/k/v + to_out.0."""

    def __init__(self, dim, heads):
        super().__init__()
        self.heads = heads
        self.to_q = nn.Linear(dim, dim, bias=True)
        self.to_k = nn.Linear(dim, dim, bias=True)
        self.to_v = nn.Linear(dim, dim, bias=True)
        self.to_out = nn.ModuleList([nn.Linear(dim, dim), nn.Dropout(0.0)])

    def forward(self, hidden, encoder_hidden_states):
        b, s, d = hidden.shape
        hd = d // self.heads
        q = self.to_q(hidden).view(b, s, self.heads, hd).transpose(1, 2)
        sk = encoder_hidden_states.shape[1]
        k = self.to_k(encoder_hidden_states).view(
            b, sk, self.heads, hd
        ).transpose(1, 2)
        v = self.to_v(encoder_hidden_states).view(
            b, sk, self.heads, hd
        ).transpose(1, 2)
        out = F.scaled_dot_product_attention(q, k, v)
        out = out.transpose(1, 2).reshape(b, s, d)
        return self.to_out[0](out)


class AttnBlockT(nn.Module):
    def __init__(self, c, c_cond, nhead, self_attn=True):
        super().__init__()
        self.self_attn = self_attn
        self.norm = LayerNorm2dT(c, elementwise_affine=False, eps=1e-6)
        self.attention = AttentionT(c, nhead)
        self.kv_mapper = nn.Sequential(nn.SiLU(), nn.Linear(c_cond, c))

    def forward(self, x, kv):
        kv = self.kv_mapper(kv)
        norm_x = self.norm(x)
        b, c, h, w = x.shape
        tokens = norm_x.view(b, c, h * w).transpose(1, 2)
        if self.self_attn:
            kv = torch.cat([tokens, kv], dim=1)
        out = self.attention(tokens, kv)
        return x + out.transpose(1, 2).view(b, c, h, w)


class UpDownBlock2dT(nn.Module):
    def __init__(self, in_channels, out_channels, mode, enabled=True):
        super().__init__()
        interpolation = (
            nn.Upsample(
                scale_factor=2 if mode == "up" else 0.5,
                mode="bilinear",
                align_corners=True,
            )
            if enabled
            else nn.Identity()
        )
        mapping = nn.Conv2d(in_channels, out_channels, kernel_size=1)
        self.blocks = nn.ModuleList(
            [interpolation, mapping] if mode == "up" else [mapping, interpolation]
        )

    def forward(self, x):
        for block in self.blocks:
            x = block(x)
        return x


class StableCascadeUNetT(nn.Module):
    """Mirror driven by the SAME CascadeUNetConfig dataclass the flax
    module uses, emitting the diffusers key layout."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        levels = len(cfg.block_out_channels)
        c0 = cfg.block_out_channels[0]

        self.clip_txt_pooled_mapper = nn.Linear(
            cfg.clip_text_pooled_in_channels,
            cfg.conditioning_dim * cfg.clip_seq,
        )
        if cfg.clip_text_in_channels:
            self.clip_txt_mapper = nn.Linear(
                cfg.clip_text_in_channels, cfg.conditioning_dim
            )
        if cfg.clip_image_in_channels:
            self.clip_img_mapper = nn.Linear(
                cfg.clip_image_in_channels,
                cfg.conditioning_dim * cfg.clip_seq,
            )
        self.clip_norm = nn.LayerNorm(
            cfg.conditioning_dim, elementwise_affine=False, eps=1e-6
        )

        self.embedding = nn.Sequential(
            nn.PixelUnshuffle(cfg.patch_size),
            nn.Conv2d(
                cfg.in_channels * cfg.patch_size**2, c0, kernel_size=1
            ),
            LayerNorm2dT(c0, elementwise_affine=False, eps=1e-6),
        )
        if cfg.effnet_in_channels:
            self.effnet_mapper = nn.Sequential(
                nn.Conv2d(cfg.effnet_in_channels, c0 * 4, kernel_size=1),
                nn.GELU(),
                nn.Conv2d(c0 * 4, c0, kernel_size=1),
                LayerNorm2dT(c0, elementwise_affine=False, eps=1e-6),
            )
        if cfg.pixel_mapper_in_channels:
            self.pixels_mapper = nn.Sequential(
                nn.Conv2d(cfg.pixel_mapper_in_channels, c0 * 4, kernel_size=1),
                nn.GELU(),
                nn.Conv2d(c0 * 4, c0, kernel_size=1),
                LayerNorm2dT(c0, elementwise_affine=False, eps=1e-6),
            )

        def make_level(level, n_layers, c_skip_first):
            ch = cfg.block_out_channels[level]
            blocks = nn.ModuleList()
            for layer in range(n_layers):
                blocks.append(
                    ResBlockT(
                        ch,
                        c_skip=c_skip_first if layer == 0 else 0,
                        kernel_size=cfg.kernel_size,
                    )
                )
                blocks.append(
                    TimestepBlockT(
                        ch,
                        cfg.timestep_ratio_embedding_dim,
                        conds=cfg.timestep_conditioning_type,
                    )
                )
                if cfg.attention[level]:
                    blocks.append(
                        AttnBlockT(
                            ch,
                            cfg.conditioning_dim,
                            cfg.num_attention_heads[level],
                            self_attn=cfg.self_attn,
                        )
                    )
            return blocks

        self.down_blocks = nn.ModuleList()
        self.down_downscalers = nn.ModuleList()
        self.down_repeat_mappers = nn.ModuleList()
        for i in range(levels):
            if i > 0:
                scaler = (
                    UpDownBlock2dT(
                        cfg.block_out_channels[i - 1],
                        cfg.block_out_channels[i],
                        mode="down",
                        enabled=cfg.switch_level[i - 1],
                    )
                    if cfg.switch_level is not None
                    else nn.Conv2d(
                        cfg.block_out_channels[i - 1],
                        cfg.block_out_channels[i],
                        kernel_size=2,
                        stride=2,
                    )
                )
                self.down_downscalers.append(
                    nn.Sequential(
                        LayerNorm2dT(
                            cfg.block_out_channels[i - 1],
                            elementwise_affine=False,
                            eps=1e-6,
                        ),
                        scaler,
                    )
                )
            else:
                self.down_downscalers.append(nn.Identity())
            self.down_blocks.append(
                make_level(i, cfg.down_num_layers_per_block[i], 0)
            )
            self.down_repeat_mappers.append(
                nn.ModuleList(
                    [
                        nn.Conv2d(
                            cfg.block_out_channels[i],
                            cfg.block_out_channels[i],
                            kernel_size=1,
                        )
                        for _ in range(cfg.down_blocks_repeat_mappers[i] - 1)
                    ]
                )
            )

        self.up_blocks = nn.ModuleList()
        self.up_upscalers = nn.ModuleList()
        self.up_repeat_mappers = nn.ModuleList()
        for j in range(levels):
            i = levels - 1 - j
            c_skip = cfg.block_out_channels[i] if j > 0 else 0
            self.up_blocks.append(
                make_level(i, cfg.up_num_layers_per_block[j], c_skip)
            )
            if i > 0:
                scaler = (
                    UpDownBlock2dT(
                        cfg.block_out_channels[i],
                        cfg.block_out_channels[i - 1],
                        mode="up",
                        enabled=cfg.switch_level[i - 1],
                    )
                    if cfg.switch_level is not None
                    else nn.ConvTranspose2d(
                        cfg.block_out_channels[i],
                        cfg.block_out_channels[i - 1],
                        kernel_size=2,
                        stride=2,
                    )
                )
                self.up_upscalers.append(
                    nn.Sequential(
                        LayerNorm2dT(
                            cfg.block_out_channels[i],
                            elementwise_affine=False,
                            eps=1e-6,
                        ),
                        scaler,
                    )
                )
            else:
                self.up_upscalers.append(nn.Identity())
            self.up_repeat_mappers.append(
                nn.ModuleList(
                    [
                        nn.Conv2d(
                            cfg.block_out_channels[i],
                            cfg.block_out_channels[i],
                            kernel_size=1,
                        )
                        for _ in range(cfg.up_blocks_repeat_mappers[j] - 1)
                    ]
                )
            )

        self.clf = nn.Sequential(
            LayerNorm2dT(c0, elementwise_affine=False, eps=1e-6),
            nn.Conv2d(
                c0, cfg.out_channels * cfg.patch_size**2, kernel_size=1
            ),
            nn.PixelShuffle(cfg.patch_size),
        )

    def gen_r_embedding(self, r, max_positions=10000):
        dim = self.cfg.timestep_ratio_embedding_dim
        r = r * max_positions
        half = dim // 2
        emb = math.log(max_positions) / (half - 1)
        emb = torch.arange(half, dtype=torch.float32).mul(-emb).exp()
        emb = r[:, None] * emb[None, :]
        emb = torch.cat([emb.sin(), emb.cos()], dim=1)
        if dim % 2 == 1:
            emb = F.pad(emb, (0, 1), mode="constant")
        return emb

    def forward(
        self,
        sample,
        timestep_ratio,
        clip_text_pooled,
        clip_text=None,
        clip_img=None,
        effnet=None,
        pixels=None,
    ):
        cfg = self.cfg
        b = sample.shape[0]
        t_embed = self.gen_r_embedding(timestep_ratio)
        for _ in cfg.timestep_conditioning_type:
            t_embed = torch.cat(
                [t_embed, self.gen_r_embedding(torch.zeros_like(timestep_ratio))],
                dim=1,
            )

        ctp = self.clip_txt_pooled_mapper(clip_text_pooled).view(
            b, clip_text_pooled.shape[1] * cfg.clip_seq, -1
        )
        if cfg.clip_text_in_channels and clip_text is not None:
            pieces = [self.clip_txt_mapper(clip_text)]
            if cfg.clip_image_in_channels:
                if clip_img is None:
                    clip_img = sample.new_zeros(
                        b, 1, cfg.clip_image_in_channels
                    )
                pieces.append(
                    self.clip_img_mapper(clip_img).view(
                        b, clip_img.shape[1] * cfg.clip_seq, -1
                    )
                )
            clip = torch.cat(pieces + [ctp], dim=1)
        else:
            clip = ctp
        clip = self.clip_norm(clip)

        x = self.embedding(sample)
        if cfg.effnet_in_channels and effnet is not None:
            x = x + self.effnet_mapper(
                F.interpolate(
                    effnet, size=x.shape[-2:], mode="bilinear",
                    align_corners=True,
                )
            )
        if cfg.pixel_mapper_in_channels:
            if pixels is None:
                pixels = sample.new_zeros(b, cfg.pixel_mapper_in_channels, 8, 8)
            x = x + F.interpolate(
                self.pixels_mapper(pixels),
                size=x.shape[-2:],
                mode="bilinear",
                align_corners=True,
            )

        def run_blocks(blocks, x, skip=None):
            first = True
            for block in blocks:
                if isinstance(block, ResBlockT):
                    s = skip if first else None
                    if s is not None and x.shape[-2:] != s.shape[-2:]:
                        x = F.interpolate(
                            x, size=s.shape[-2:], mode="bilinear",
                            align_corners=True,
                        )
                    x = block(x, s)
                    first = False
                elif isinstance(block, TimestepBlockT):
                    x = block(x, t_embed)
                else:
                    x = block(x, clip)
            return x

        level_outputs = []
        for i, (blocks, scaler, repmap) in enumerate(
            zip(self.down_blocks, self.down_downscalers, self.down_repeat_mappers)
        ):
            x = scaler(x)
            for r in range(len(repmap) + 1):
                x = run_blocks(blocks, x)
                if r < len(repmap):
                    x = repmap[r](x)
            level_outputs.insert(0, x)

        x = level_outputs[0]
        for j, (blocks, scaler, repmap) in enumerate(
            zip(self.up_blocks, self.up_upscalers, self.up_repeat_mappers)
        ):
            skip = level_outputs[j] if j > 0 else None
            for r in range(len(repmap) + 1):
                x = run_blocks(blocks, x, skip=skip)
                if r < len(repmap):
                    x = repmap[r](x)
            x = scaler(x)
        return self.clf(x)


class MixingResidualBlockT(nn.Module):
    def __init__(self, inp_channels, embed_dim):
        super().__init__()
        self.norm1 = LayerNorm2dT(inp_channels, elementwise_affine=False, eps=1e-6)
        self.depthwise = nn.Sequential(
            nn.ReplicationPad2d(1),
            nn.Conv2d(inp_channels, inp_channels, kernel_size=3, groups=inp_channels),
        )
        self.norm2 = LayerNorm2dT(inp_channels, elementwise_affine=False, eps=1e-6)
        self.channelwise = nn.Sequential(
            nn.Linear(inp_channels, embed_dim),
            nn.GELU(),
            nn.Linear(embed_dim, inp_channels),
        )
        self.gammas = nn.Parameter(torch.zeros(6), requires_grad=True)

    def forward(self, x):
        mods = self.gammas
        x_temp = self.norm1(x) * (1 + mods[0]) + mods[1]
        x = x + self.depthwise(x_temp) * mods[2]
        x_temp = self.norm2(x) * (1 + mods[3]) + mods[4]
        x = (
            x
            + self.channelwise(x_temp.permute(0, 2, 3, 1)).permute(0, 3, 1, 2)
            * mods[5]
        )
        return x


class PaellaVQT(nn.Module):
    """PaellaVQModel mirror (decode path exercised; encoder keys exist so
    the converter's ignore-list is tested on real layouts)."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        c_levels = cfg.c_levels()
        self.in_block = nn.Sequential(
            nn.PixelUnshuffle(cfg.up_down_scale_factor),
            nn.Conv2d(
                cfg.out_channels * cfg.up_down_scale_factor**2,
                c_levels[0],
                kernel_size=1,
            ),
        )
        down_blocks = []
        for i in range(cfg.levels):
            if i > 0:
                down_blocks.append(
                    nn.Conv2d(
                        c_levels[i - 1], c_levels[i], kernel_size=4,
                        stride=2, padding=1,
                    )
                )
            down_blocks.append(
                MixingResidualBlockT(c_levels[i], c_levels[i] * 4)
            )
        down_blocks.append(
            nn.Sequential(
                nn.Conv2d(
                    c_levels[-1], cfg.latent_channels, kernel_size=1,
                    bias=False,
                ),
                nn.BatchNorm2d(cfg.latent_channels),
            )
        )
        self.down_blocks = nn.Sequential(*down_blocks)

        up_blocks = [nn.Sequential(nn.Conv2d(cfg.latent_channels, c_levels[-1], kernel_size=1))]
        for i in range(cfg.levels):
            for j in range(cfg.bottleneck_blocks if i == 0 else 1):
                up_blocks.append(
                    MixingResidualBlockT(
                        c_levels[cfg.levels - 1 - i],
                        c_levels[cfg.levels - 1 - i] * 4,
                    )
                )
            if i < cfg.levels - 1:
                up_blocks.append(
                    nn.ConvTranspose2d(
                        c_levels[cfg.levels - 1 - i],
                        c_levels[cfg.levels - 2 - i],
                        kernel_size=4,
                        stride=2,
                        padding=1,
                    )
                )
        self.up_blocks = nn.Sequential(*up_blocks)
        self.out_block = nn.Sequential(
            nn.Conv2d(
                c_levels[0],
                cfg.out_channels * cfg.up_down_scale_factor**2,
                kernel_size=1,
            ),
            nn.PixelShuffle(cfg.up_down_scale_factor),
        )

    def decode(self, latents):
        return self.out_block(self.up_blocks(latents))
