"""SD-x2 latent upscaler conversion contract (VERDICT r03 missing #1).

The checkpoint side is the torch mirror in torch_unet_ref.py
(KUpscalerUNetT, exact diffusers key names): random torch init -> state
dict -> convert -> flax forward must equal the torch forward, including
the Gaussian-Fourier time path, the 896-d timestep condition, AdaGroupNorm
modulation, fixed blur down/upsampling, and the K-UNet skip wiring. A full
synthetic repo (UNet + CLIP + VAE) must pass `initialize --check` and
serve a 2x upscale end-to-end with converted weights.
"""

import json
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chiaswarm_tpu.models.conversion import convert_k_upscaler
from chiaswarm_tpu.models.k_upscaler import (
    TINY_K_UPSCALER,
    KUpscalerUNet,
)

sys.path.insert(0, os.path.dirname(__file__))

torch = pytest.importorskip("torch")

from torch_unet_ref import KUpscalerUNetT  # noqa: E402


def _state_numpy(module) -> dict:
    return {k: v.detach().numpy() for k, v in module.state_dict().items()}


@pytest.fixture(scope="module")
def mirror():
    torch.manual_seed(50)
    m = KUpscalerUNetT(TINY_K_UPSCALER)
    m.eval()
    return m


def test_k_upscaler_config_inferred(mirror):
    cfg, _ = convert_k_upscaler(
        _state_numpy(mirror),
        {"attention_head_dim": TINY_K_UPSCALER.attention_head_dim,
         "resnet_group_size": TINY_K_UPSCALER.resnet_group_size},
    )
    assert cfg == TINY_K_UPSCALER


def test_k_upscaler_torch_parity(mirror):
    cfg, params = convert_k_upscaler(
        _state_numpy(mirror),
        {"attention_head_dim": TINY_K_UPSCALER.attention_head_dim,
         "resnet_group_size": TINY_K_UPSCALER.resnet_group_size},
    )
    rng = np.random.default_rng(51)
    b, hw, s = 2, 16, 7
    sample = rng.standard_normal((b, hw, hw, cfg.in_channels)).astype(
        np.float32
    )
    # continuous K-diffusion timesteps (log-sigma scale, can be negative)
    t = np.asarray([-0.55, 0.6], np.float32)
    ctx = rng.standard_normal((b, s, cfg.cross_attention_dim)).astype(
        np.float32
    )
    tcond = rng.standard_normal((b, cfg.time_cond_proj_dim)).astype(
        np.float32
    )

    with torch.no_grad():
        out_t = mirror(
            torch.from_numpy(sample).permute(0, 3, 1, 2),
            torch.from_numpy(t),
            torch.from_numpy(ctx),
            torch.from_numpy(tcond),
        ).permute(0, 2, 3, 1).numpy()

    out_f = KUpscalerUNet(cfg).apply(
        {"params": params}, jnp.asarray(sample), jnp.asarray(t),
        jnp.asarray(ctx), jnp.asarray(tcond),
    )
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=3e-4, rtol=1e-3)


def test_full_upscaler_repo_check_and_pipeline(sdaas_root, tmp_path):
    """A complete synthetic sd-x2 repo — torch-mirror K-UNet, a REAL
    transformers CLIPTextModel state dict, torch-mirror VAE — passes
    `initialize --check` AND serves a 2x upscale with converted weights
    (reference swarm/post_processors/upscale.py:5-36)."""
    from PIL import Image
    from safetensors.numpy import save_file
    from transformers import CLIPTextConfig as HFCLIPTextConfig
    from transformers import CLIPTextModel

    from torch_unet_ref import AutoencoderKLT

    from chiaswarm_tpu.initialize import verify_local_model
    from chiaswarm_tpu.models import configs as cfgs
    from chiaswarm_tpu.pipelines.upscale import LatentUpscalePipeline
    from chiaswarm_tpu.settings import Settings, save_settings

    name = "stabilityai/sd-x2-latent-upscaler"
    root = tmp_path / "models"
    save_settings(Settings(model_root_dir=str(root)))
    repo = root / name
    torch.manual_seed(52)

    (repo / "unet").mkdir(parents=True)
    save_file(
        _state_numpy(KUpscalerUNetT(TINY_K_UPSCALER)),
        str(repo / "unet" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "unet" / "config.json").write_text(json.dumps({
        "attention_head_dim": TINY_K_UPSCALER.attention_head_dim,
        "resnet_group_size": TINY_K_UPSCALER.resnet_group_size,
    }))

    hf = HFCLIPTextConfig(
        vocab_size=1000, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=128,
        max_position_embeddings=77, hidden_act="quick_gelu",
    )
    clip = CLIPTextModel(hf)
    (repo / "text_encoder").mkdir(parents=True)
    save_file(
        {k: v.numpy() for k, v in clip.state_dict().items()},
        str(repo / "text_encoder" / "model.safetensors"),
    )
    (repo / "text_encoder" / "config.json").write_text(json.dumps({
        "vocab_size": 1000, "hidden_size": 32, "num_hidden_layers": 2,
        "num_attention_heads": 4, "hidden_act": "quick_gelu",
    }))

    vae = AutoencoderKLT(cfgs.TINY_VAE)
    (repo / "vae").mkdir(parents=True)
    save_file(
        _state_numpy(vae),
        str(repo / "vae" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "vae" / "config.json").write_text(json.dumps({
        "scaling_factor": 0.18215,
    }))

    (repo / "scheduler").mkdir(parents=True)
    (repo / "scheduler" / "scheduler_config.json").write_text(json.dumps({
        "prediction_type": "sample",
        "beta_start": 0.0001,
        "beta_end": 0.02,
        "beta_schedule": "linear",
    }))

    report = verify_local_model(name, root)
    assert report is not None
    assert set(report) == {"unet", "text_encoder", "vae"}

    pipe = LatentUpscalePipeline(name)
    assert pipe.scheduler_json["prediction_type"] == "sample"
    img = Image.fromarray(
        (np.random.default_rng(53).random((64, 64, 3)) * 255).astype(
            np.uint8
        )
    )
    out = pipe.upscale([img], prompt="sharp", steps=2, rng=jax.random.key(54))
    assert out[0].size == (128, 128)
