"""Extended ControlNet preprocessors (VERDICT missing #4 tail): mlsd,
lineart, normal-bae, segmentation, zoe depth, openpose, pix2pix identity,
and the reference's spaced wire-name spellings (controlnet.py:25-75).
"""

import numpy as np
import pytest
from PIL import Image

from chiaswarm_tpu.pre_processors.controlnet import (
    ADE_STYLE_PALETTE,
    preprocess_image,
)
from chiaswarm_tpu.settings import Settings, save_settings


def _image(seed=0, size=64):
    rng = np.random.default_rng(seed)
    arr = (rng.random((size, size, 3)) * 255).astype(np.uint8)
    # add structure so edge/line detectors have something to find
    arr[size // 4: size // 2, :, :] = 240
    arr[:, size // 3, :] = 0
    return Image.fromarray(arr)


@pytest.fixture()
def tiny_aux(sdaas_root):
    save_settings(
        Settings(depth_model="test/tiny-dpt", pose_model="test/tiny-pose")
    )


def test_pix2pix_identity():
    img = _image(0)
    assert preprocess_image(img, "pix2pix", "cpu:0") is img


def test_mlsd_wireframe():
    out = np.asarray(preprocess_image(_image(1, 128), "mlsd", "cpu:0"))
    assert out.shape == (128, 128, 3)
    # white-on-black: strictly binary palette
    assert set(np.unique(out)) <= {0, 255}


def test_lineart_strokes():
    out = np.asarray(preprocess_image(_image(2, 96), "lineart", "cpu:0"))
    assert out.shape == (96, 96, 3)
    np.testing.assert_array_equal(out[..., 0], out[..., 1])
    assert out.max() > 0  # found some strokes in the structured image


def test_normal_bae_unit_vectors(tiny_aux):
    out = np.asarray(
        preprocess_image(_image(3, 64), "normal bae", "cpu:0"), np.float32
    )
    n = out / 255.0 * 2.0 - 1.0
    norms = np.sqrt((n**2).sum(axis=-1))
    # decoded normals are unit-ish (8-bit quantization slack)
    assert float(np.abs(norms - 1.0).max()) < 0.05
    # z points mostly toward the camera
    assert float(n[..., 2].mean()) > 0.3


def test_normal_bae_dashed_alias(tiny_aux):
    out = preprocess_image(_image(3, 64), "Normal-BAE", "cpu:0")
    assert out.size == (64, 64)


def test_zoe_depth(tiny_aux):
    out = np.asarray(preprocess_image(_image(4, 64), "zoe depth", "cpu:0"))
    assert out.shape == (64, 64, 3)
    np.testing.assert_array_equal(out[..., 0], out[..., 2])


def test_depth_estimator_hint(tiny_aux):
    out = np.asarray(
        preprocess_image(_image(5, 64), "depth estimator", "cpu:0")
    )
    assert out.shape == (64, 64, 3)


def test_segmentation_palette_map():
    img = _image(6, 80)
    out = np.asarray(preprocess_image(img, "segmentation", "cpu:0"))
    assert out.shape == (80, 80, 3)
    palette = {tuple(c) for c in ADE_STYLE_PALETTE}
    seen = {tuple(px) for px in out.reshape(-1, 3)}
    assert seen <= palette
    assert 2 <= len(seen) <= 12
    # deterministic across runs (fixed-seed kmeans)
    out2 = np.asarray(preprocess_image(img, "segmentation", "cpu:0"))
    np.testing.assert_array_equal(out, out2)


def test_openpose_skeleton(tiny_aux):
    out = np.asarray(preprocess_image(_image(7, 96), "openpose", "cpu:0"))
    assert out.shape == (96, 96, 3)
    assert out.max() > 0  # some limbs/joints rendered


def test_openpose_real_weights_fail_loud(sdaas_root):
    from chiaswarm_tpu.pipelines.aux_models import PoseEstimator
    from chiaswarm_tpu.weights import MissingWeightsError

    with pytest.raises(MissingWeightsError):
        PoseEstimator("lllyasviel/ControlNet-openpose")


def test_soft_edge_spaced_alias():
    out = preprocess_image(_image(8, 64), "soft edge", "cpu:0")
    assert out.size == (64, 64)


def test_center_crop_alias():
    out = preprocess_image(_image(9, 100), "center crop", "cpu:0")
    assert out.size == (512, 512)


def test_unknown_preprocessor_raises():
    with pytest.raises(ValueError, match="Unknown or unavailable"):
        preprocess_image(_image(0), "frobnicate", "cpu:0")


class TestHED:
    def test_conversion_roundtrip(self):
        import jax
        import jax.numpy as jnp

        from chiaswarm_tpu.models.conversion import convert_hed
        from chiaswarm_tpu.models.hed import HEDNet, TINY_HED

        net = HEDNet(TINY_HED)
        params = net.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))["params"]
        # synthesize the checkpoint layout (norm, blockN.convs.M, projection)
        state = {"norm": np.asarray(params["norm"], np.float32)}
        for bi in range(len(TINY_HED.channels)):
            blk = params[f"block{bi + 1}"]
            for ci in range(TINY_HED.layers[bi]):
                k = np.asarray(blk[f"convs_{ci}"]["kernel"], np.float32)
                state[f"block{bi + 1}.convs.{ci}.weight"] = (
                    np.ascontiguousarray(k.transpose(3, 2, 0, 1))
                )
                state[f"block{bi + 1}.convs.{ci}.bias"] = np.asarray(
                    blk[f"convs_{ci}"]["bias"], np.float32
                )
            pk = np.asarray(blk["projection"]["kernel"], np.float32)
            state[f"block{bi + 1}.projection.weight"] = np.ascontiguousarray(
                pk.transpose(3, 2, 0, 1)
            )
            state[f"block{bi + 1}.projection.bias"] = np.asarray(
                blk["projection"]["bias"], np.float32
            )
        converted = convert_hed(state)
        flat_a = jax.tree_util.tree_leaves(converted)
        flat_b = jax.tree_util.tree_leaves(params)
        assert len(flat_a) == len(flat_b)
        for a, b in zip(
            jax.tree_util.tree_leaves_with_path(converted),
            jax.tree_util.tree_leaves_with_path(params),
        ):
            np.testing.assert_allclose(
                np.asarray(a[1], np.float32), np.asarray(b[1], np.float32),
                rtol=1e-6, err_msg=str(a[0]),
            )

    def test_scribble_differs_from_softedge_with_hed(self, monkeypatch):
        # with a (stubbed) HED map, scribble is thinned binary, softedge is
        # the soft map — the round-2 complaint was that both were one fn
        from chiaswarm_tpu.pipelines import aux_models
        from chiaswarm_tpu.pre_processors import controlnet as pp

        rng = np.random.default_rng(0)
        soft = rng.random((48, 48)).astype(np.float32)
        monkeypatch.setattr(aux_models, "hed_edges", lambda img: soft)
        img = Image.fromarray((rng.random((48, 48, 3)) * 255).astype(np.uint8))
        s = np.asarray(pp.preprocess_image(img, "scribble", "cpu:0"))
        e = np.asarray(pp.preprocess_image(img, "softedge", "cpu:0"))
        assert set(np.unique(s)).issubset({0, 255})  # thinned binary
        assert len(np.unique(e)) > 2  # soft probabilities
        assert not np.array_equal(s, e)

    def test_fallback_without_weights(self, sdaas_root):
        # no converted HED weights: the classical heuristic serves the job
        from chiaswarm_tpu.pre_processors import controlnet as pp

        img = Image.new("RGB", (32, 32), (120, 50, 200))
        out = pp.preprocess_image(img, "softedge", "cpu:0")
        assert out.size == (32, 32)
