"""Priority-aware multi-chip sharding (ISSUE 12): one job, many chips.

Covers the elastic slice geometry end to end on the 8-virtual-device CPU
mesh: per-pass geometry selection (an interactive solo fans one image
over the whole slice as a tensor-sharded program while coalesced batch
traffic keeps the data-parallel view), the chunk-boundary re-shard seam
(a pass migrated sharded->replicated — or back — mid-denoise equals an
undisturbed pass), cancellation probing under a sharded mesh, the
worker-side class routing, and the hive-side shard-capable dispatch
preference.
"""

import asyncio
import base64

import numpy as np
import pytest

import jax

from chiaswarm_tpu import cancel as cancel_mod
from chiaswarm_tpu import worker as worker_mod
from chiaswarm_tpu.cancel import JobCancelled
from chiaswarm_tpu.chips.allocator import SliceAllocator
from chiaswarm_tpu.chips.device import ChipSet
from chiaswarm_tpu.pipelines.stable_diffusion import (
    SDPipeline,
    geometry_label,
)
from chiaswarm_tpu.settings import Settings
from chiaswarm_tpu.telemetry import trace_job
from chiaswarm_tpu.worker import Worker

from .fake_hive import FakeHive


@pytest.fixture(autouse=True)
def fast_poll(monkeypatch):
    monkeypatch.setattr(worker_mod, "POLL_SECONDS", 0.05)
    monkeypatch.setattr(worker_mod, "ERROR_BACKOFF_SECONDS", 0.2)


@pytest.fixture(scope="module")
def slice8():
    return ChipSet(jax.devices())  # 8 virtual CPU chips, one slice


@pytest.fixture(scope="module")
def pipe8(slice8):
    return SDPipeline("test/tiny-sd", chipset=slice8)


KW = dict(prompt="geometry test", height=64, width=64,
          num_inference_steps=4)


# --- geometry resolution ----------------------------------------------------


def test_chipset_resolve_geometry():
    cs = ChipSet(jax.devices())
    assert cs.shard_capable
    # auto leaves a data axis for the CFG pair: 8 chips -> tensor=4
    assert cs.resolve_geometry(0, 1) == (4, 1)
    assert cs.resolve_geometry(None, None) == (4, 1)
    assert cs.resolve_geometry(2, 1) == (2, 1)
    assert cs.resolve_geometry(8, 1) == (8, 1)
    assert cs.resolve_geometry(0, 2) == (2, 2)  # auto under a seq axis
    assert cs.resolve_geometry(3, 1) is None  # 3 does not divide 8
    assert cs.resolve_geometry(2, 3) is None
    solo = ChipSet(jax.devices()[:1])
    assert not solo.shard_capable
    assert solo.resolve_geometry(0, 1) == (1, 1)
    assert solo.resolve_geometry(2, 1) is None


def test_geometry_label():
    assert geometry_label(1, 1) == "replicated"
    assert geometry_label(2, 1) == "tensor2"
    assert geometry_label(1, 2) == "seq2"
    assert geometry_label(2, 2) == "tensor2_seq2"


# --- per-pass geometry selection -------------------------------------------


def test_sharded_pass_matches_replicated_and_stamps(pipe8):
    ref, cfg0 = pipe8.run(rng=jax.random.key(3), **KW)
    assert cfg0["geometry"] == {"data": 8, "tensor": 1, "seq": 1}

    imgs, cfg = pipe8.run(rng=jax.random.key(3),
                          geometry={"tensor": 2}, **KW)
    assert cfg["geometry"] == {"data": 4, "tensor": 2, "seq": 1}
    diff = np.abs(np.asarray(ref[0], np.int16)
                  - np.asarray(imgs[0], np.int16))
    assert diff.max() <= 2, f"max pixel diff {diff.max()}"
    # the slice remembers the view its latest pass ran under
    assert pipe8.chipset.last_geometry == (4, 2, 1)
    assert pipe8.chipset.geometry_str() == "data4·tensor2·seq1"


def test_unmeshable_geometry_falls_back_to_default(pipe8):
    imgs, cfg = pipe8.run(rng=jax.random.key(3),
                          geometry={"tensor": 3}, **KW)
    assert cfg["geometry"] == {"data": 8, "tensor": 1, "seq": 1}
    assert len(imgs) == 1


def test_sharded_pass_counter(pipe8):
    from chiaswarm_tpu import telemetry

    before = telemetry.REGISTRY.render()
    pipe8.run(rng=jax.random.key(4), geometry={"tensor": 2}, **KW)
    after = telemetry.REGISTRY.render()
    line = 'swarm_sharded_passes_total{geometry="tensor2"}'
    count = lambda text: next(
        (float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
         if ln.startswith(line)), 0.0)
    assert count(after) == count(before) + 1


# --- the chunk-seam re-shard ------------------------------------------------


def test_reshard_midpass_matches_undisturbed(pipe8, sdaas_root, monkeypatch):
    monkeypatch.setenv("CHIASWARM_DENOISE_CHUNK_STEPS", "1")
    ref, _ = pipe8.run(rng=jax.random.key(9), **KW)

    # sharded -> replicated after the first boundary
    down, cfg_down = pipe8.run(rng=jax.random.key(9),
                               geometry={"tensor": 2},
                               reshard_probe=lambda: "default", **KW)
    assert cfg_down["resharded"], cfg_down
    assert cfg_down["resharded"][0]["from"] == [2, 1]
    assert cfg_down["resharded"][0]["to"] == [1, 1]
    diff = np.abs(np.asarray(ref[0], np.int16)
                  - np.asarray(down[0], np.int16))
    assert diff.max() <= 2, f"down-migrated diff {diff.max()}"

    # replicated -> sharded (the reverse seam)
    up, cfg_up = pipe8.run(rng=jax.random.key(9),
                           reshard_probe=lambda: {"tensor": 2}, **KW)
    assert cfg_up["resharded"][0]["to"] == [2, 1]
    diff = np.abs(np.abs(np.asarray(ref[0], np.int16)
                         - np.asarray(up[0], np.int16)))
    assert diff.max() <= 2, f"up-migrated diff {diff.max()}"


def test_reshard_probe_none_keeps_geometry(pipe8, sdaas_root, monkeypatch):
    monkeypatch.setenv("CHIASWARM_DENOISE_CHUNK_STEPS", "2")
    imgs, cfg = pipe8.run(rng=jax.random.key(10),
                          geometry={"tensor": 2},
                          reshard_probe=lambda: None, **KW)
    assert "resharded" not in cfg
    assert cfg["geometry"]["tensor"] == 2
    assert len(imgs) == 1


def test_cancel_probed_at_chunk_boundary_under_mesh(pipe8, sdaas_root,
                                                    monkeypatch):
    """ISSUE 12 satellite: the cancel token is still probed at chunk
    boundaries when the pass runs under a sharded mesh — a revoked
    interactive job frees its whole-slice sharded pass within one
    chunk, exactly like a replicated one."""
    monkeypatch.setenv("CHIASWARM_DENOISE_CHUNK_STEPS", "1")
    cancel_mod.cancel("doomed-sharded")
    try:
        with trace_job("doomed-sharded"):
            with pytest.raises(JobCancelled) as err:
                pipe8.run(rng=jax.random.key(5),
                          geometry={"tensor": 2}, **KW)
        assert err.value.job_ids == ["doomed-sharded"]
    finally:
        cancel_mod.discard("doomed-sharded")


# --- worker-side class routing ---------------------------------------------


def test_worker_interactive_shards_batch_coalesces(sdaas_root):
    """The class picks the view end-to-end on ONE allocator: an
    interactive job executes under a tensor>1 mesh (geometry stamped in
    its envelope) while a concurrent batch group keeps data-parallel
    coalescing on the same 8-chip slice."""

    def sd_job(jid: str, **extra) -> dict:
        job = {"id": jid, "workflow": "txt2img",
               "model_name": "stabilityai/stable-diffusion-2-1",
               "prompt": f"subject {jid}", "height": 64, "width": 64,
               "num_inference_steps": 2,
               "parameters": {"test_tiny_model": True}}
        job.update(extra)
        return job

    jobs = [sd_job(f"batch-{i}") for i in range(3)]
    # distinct step count -> its own coalesce bucket, so the interactive
    # job dispatches solo instead of riding the batch group
    jobs.append(sd_job("vip", num_inference_steps=4,
                       priority="interactive"))

    async def scenario():
        hive = await FakeHive().start()
        for job in jobs:
            hive.add_job(job)
        settings = Settings(sdaas_token="test-token",
                            worker_name="shard-worker",
                            shard_interactive=True, shard_tensor=2)
        w = Worker(settings=settings,
                   allocator=SliceAllocator(chips_per_job=0),
                   hive_uri=hive.uri)
        runner = asyncio.create_task(w.run())
        try:
            results = await hive.wait_for_results(4, timeout=240.0)
        finally:
            w.stop()
            await asyncio.wait_for(runner, 10)
            await hive.stop()
        return hive, results

    hive, results = asyncio.run(scenario())
    by_id = {r["id"]: r for r in results}
    vip = by_id["vip"]["pipeline_config"]
    assert vip["geometry"]["tensor"] == 2, vip
    assert vip["geometry"]["data"] == 4, vip
    for i in range(3):
        cfg = by_id[f"batch-{i}"]["pipeline_config"]
        assert cfg["geometry"] == {"data": 8, "tensor": 1, "seq": 1}, cfg
        assert cfg["batched_with"] == 3, cfg
        blob = by_id[f"batch-{i}"]["artifacts"]["primary"]["blob"]
        assert base64.b64decode(blob).startswith(b"\xff\xd8")
    # the worker advertised its slice geometry on /work
    req = hive.work_requests[0]
    assert req["chips_per_slice"] == "8"
    assert req["shard_capable"] == "1"


def test_worker_shard_geometry_gates(sdaas_root):
    """No sharding without the knob, on single-chip slices, or when the
    resolved view equals the slice default."""
    alloc = SliceAllocator(chips_per_job=0)
    w = Worker(settings=Settings(sdaas_token="t"), allocator=alloc,
               hive_uri="http://127.0.0.1:1")
    assert w._shard_geometry(alloc.slices[0]) is None  # knob off

    w2 = Worker(settings=Settings(sdaas_token="t", shard_interactive=True),
                allocator=alloc, hive_uri="http://127.0.0.1:1")
    assert w2._shard_geometry(alloc.slices[0]) == (4, 1)  # auto

    solo_alloc = SliceAllocator(chips_per_job=1)
    w3 = Worker(settings=Settings(sdaas_token="t", shard_interactive=True),
                allocator=solo_alloc, hive_uri="http://127.0.0.1:1")
    assert w3._shard_geometry(solo_alloc.slices[0]) is None


def test_localswarm_interactive_sharded_e2e(sdaas_root):
    """ISSUE 12 acceptance: on a LocalSwarm with an 8-device slice, an
    interactive job demonstrably executes under a tensor>1 mesh —
    geometry stamped in its settled envelope — while concurrently
    submitted batch jobs keep data-parallel coalescing (gang-dispatched
    by the hive, batched_with in their envelopes)."""
    from chiaswarm_tpu.hive_server.harness import LocalSwarm

    def sd_job(jid: str, **extra) -> dict:
        job = {"id": jid, "workflow": "txt2img",
               "model_name": "stabilityai/stable-diffusion-2-1",
               "prompt": f"swarm subject {jid}", "height": 64, "width": 64,
               "num_inference_steps": 2,
               "parameters": {"test_tiny_model": True}}
        job.update(extra)
        return job

    async def scenario():
        swarm = LocalSwarm(
            n_workers=1,
            settings=Settings(
                sdaas_token="local-swarm", worker_name="swarm-worker",
                hive_port=0, metrics_port=0,
                shard_interactive=True, shard_tensor=2))
        async with swarm:
            batch_ids = [await swarm.submit(sd_job(f"bulk-{i}"))
                         for i in range(2)]
            vip_id = await swarm.submit(
                sd_job("vip", num_inference_steps=4,
                       priority="interactive"))
            vip = await swarm.wait_done(vip_id)
            done = [await swarm.wait_done(j) for j in batch_ids]
        vip_cfg = vip["result"]["pipeline_config"]
        assert vip_cfg["geometry"]["tensor"] == 2, vip_cfg
        for status in done:
            cfg = status["result"]["pipeline_config"]
            assert cfg["geometry"]["tensor"] == 1, cfg
            assert cfg["geometry"]["data"] == 8, cfg
        return True

    assert asyncio.run(scenario())


# --- hive-side dispatch preference -----------------------------------------


def _observe(directory, name, **extra):
    query = {"worker_name": name, "worker_version": "0.1.0", "chips": "8",
             "slices": "1", "busy_slices": "0", "queue_depth": "0",
             "resident_models": ""}
    query.update({k: str(v) for k, v in extra.items()})
    return directory.observe(query)


def test_dispatch_prefers_shard_capable_for_interactive_seeds():
    from chiaswarm_tpu.hive_server.dispatch import (
        Dispatcher,
        WorkerDirectory,
    )
    from chiaswarm_tpu.hive_server.queue import PriorityJobQueue

    directory = WorkerDirectory(ttl_s=45.0)
    dispatcher = Dispatcher(directory, affinity_hold_s=60.0,
                            max_jobs_per_poll=4)
    q = PriorityJobQueue()
    q.submit({"id": "vip", "workflow": "txt2img",
              "model_name": "m", "priority": "interactive"})
    plain = _observe(directory, "plain", chips_per_slice=8, shard_capable=0)
    capable = _observe(directory, "capable", chips_per_slice=8,
                       shard_capable=1)
    # the non-capable poller is held while a shard-capable worker is live
    assert dispatcher.select(plain, q) == []
    handed = dispatcher.select(capable, q)
    assert [r.job_id for r, _, _ in handed] == ["vip"]
    assert capable.shard_capable and capable.chips_per_slice == 8


def test_shard_hold_never_starves():
    """Outside the hold window — or with no shard-capable worker live —
    any poller takes the interactive seed (preference, not a gate)."""
    from chiaswarm_tpu.hive_server.dispatch import (
        Dispatcher,
        WorkerDirectory,
    )
    from chiaswarm_tpu.hive_server.queue import PriorityJobQueue

    directory = WorkerDirectory(ttl_s=45.0)
    dispatcher = Dispatcher(directory, affinity_hold_s=0.0,
                            max_jobs_per_poll=4)
    q = PriorityJobQueue()
    q.submit({"id": "vip", "workflow": "txt2img",
              "model_name": "m", "priority": "interactive"})
    plain = _observe(directory, "plain", shard_capable=0)
    _observe(directory, "capable", shard_capable=1)
    # hold window 0: the window has lapsed by the time the poll lands
    assert [r.job_id for r, _, _ in dispatcher.select(plain, q)] == ["vip"]

    q2 = PriorityJobQueue()
    q2.submit({"id": "vip2", "workflow": "txt2img",
               "model_name": "m", "priority": "interactive"})
    lonely_dir = WorkerDirectory(ttl_s=45.0)
    lonely = _observe(lonely_dir, "plain", shard_capable=0)
    d2 = Dispatcher(lonely_dir, affinity_hold_s=60.0, max_jobs_per_poll=4)
    assert [r.job_id for r, _, _ in d2.select(lonely, q2)] == ["vip2"]


def test_shard_hold_excludes_straggler_targets():
    """A straggler-flagged shard-capable worker is NOT a shard_hold
    target: straggler_hold already withholds the seed from it, so
    counting it would make the two rules defer to each other and park
    the seed for the whole hold window while both workers poll."""
    from chiaswarm_tpu.hive_server.dispatch import (
        Dispatcher,
        WorkerDirectory,
    )
    from chiaswarm_tpu.hive_server.queue import PriorityJobQueue

    class FlagCapable:
        def note(self, *a):
            pass

        def forget(self, *a):
            pass

        def refresh_metrics(self, *a):
            pass

        def is_outlier(self, name, live):
            return name == "capable"

    directory = WorkerDirectory(ttl_s=45.0, fleet=FlagCapable())
    dispatcher = Dispatcher(directory, affinity_hold_s=60.0,
                            max_jobs_per_poll=4)
    q = PriorityJobQueue()
    q.submit({"id": "vip", "workflow": "txt2img",
              "model_name": "m", "priority": "interactive"})
    plain = _observe(directory, "plain", shard_capable=0)
    _observe(directory, "capable", shard_capable=1)
    # the only shard-capable worker is flagged: the healthy plain poller
    # takes the seed instead of waiting out the window
    assert [r.job_id for r, _, _ in dispatcher.select(plain, q)] == ["vip"]


def test_batch_jobs_ignore_shard_preference():
    from chiaswarm_tpu.hive_server.dispatch import (
        Dispatcher,
        WorkerDirectory,
    )
    from chiaswarm_tpu.hive_server.queue import PriorityJobQueue

    directory = WorkerDirectory(ttl_s=45.0)
    dispatcher = Dispatcher(directory, affinity_hold_s=60.0,
                            max_jobs_per_poll=4)
    q = PriorityJobQueue()
    q.submit({"id": "bulk", "workflow": "txt2img", "model_name": "m",
              "priority": "batch"})
    plain = _observe(directory, "plain", shard_capable=0)
    _observe(directory, "capable", shard_capable=1)
    assert [r.job_id for r, _, _ in dispatcher.select(plain, q)] == ["bulk"]
