"""AnimateDiff conversion (VERDICT r2 next #4): spatial SD-UNet renames +
MotionAdapter temporal-module overlay onto the VideoUNet tree.

diffusers isn't installed, so the checkpoint side is synthesized from the
tiny flax tree via an explicit inverse of the documented key layout, then
converted back and compared exactly (same method as
tests/test_kandinsky_conversion.py).
"""

import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chiaswarm_tpu.models import configs as cfgs
from chiaswarm_tpu.models.conversion import (
    convert_motion_adapter,
    convert_video_unet,
)
from chiaswarm_tpu.models.video_unet import VideoUNet, VideoUNetConfig


def _invert_part0(p: str) -> str:
    m = re.match(r"(down|up)_(\d+)_(resnets|attentions)_(\d+)$", p)
    if m:
        return f"{m.group(1)}_blocks.{m.group(2)}.{m.group(3)}.{m.group(4)}"
    m = re.match(r"(down|up)_(\d+)_motion_modules_(\d+)$", p)
    if m:
        return (
            f"{m.group(1)}_blocks.{m.group(2)}.motion_modules."
            f"{m.group(3)}.temporal_transformer"
        )
    m = re.match(r"down_(\d+)_downsample$", p)
    if m:
        return f"down_blocks.{m.group(1)}.downsamplers.0"
    m = re.match(r"up_(\d+)_upsample$", p)
    if m:
        return f"up_blocks.{m.group(1)}.upsamplers.0"
    m = re.match(r"mid_(resnets|attentions)_(\d+)$", p)
    if m:
        return f"mid_block.{m.group(1)}.{m.group(2)}"
    m = re.match(r"mid_motion_modules_(\d+)$", p)
    if m:
        return f"mid_block.motion_modules.{m.group(1)}.temporal_transformer"
    return p


def _invert_inner(p: str) -> str:
    p = re.sub(r"transformer_blocks_(\d+)", r"transformer_blocks.\1", p)
    p = p.replace("to_out_0", "to_out.0")
    p = p.replace("net_0", "net.0").replace("net_2", "net.2")
    return p


def _walk(tree, path=()):
    for k, v in tree.items():
        if isinstance(v, dict):
            yield from _walk(v, path + (k,))
        else:
            yield path + (k,), np.asarray(v, np.float32)


def _leaf(parts, arr):
    leaf = parts[-1]
    if leaf == "kernel":
        if arr.ndim == 4:
            return "weight", np.ascontiguousarray(arr.transpose(3, 2, 0, 1))
        return "weight", np.ascontiguousarray(arr.T)
    if leaf in ("scale", "embedding"):
        return "weight", arr
    return leaf, arr


def _synth(params):
    """Flax VideoUNet tree -> (spatial_state, motion_state) in diffusers
    naming."""
    spatial, motion = {}, {}
    for parts, arr in _walk(params):
        comps = [_invert_part0(parts[0])] + [
            _invert_inner(p) for p in parts[1:-1]
        ]
        leaf, val = _leaf(parts, arr)
        name = ".".join(comps) + f".{leaf}"
        (motion if "motion_modules" in parts[0] else spatial)[name] = val
    return spatial, motion


@pytest.fixture(scope="module")
def video_params():
    cfg = VideoUNetConfig(base=cfgs.TINY_UNET, num_frames=4)
    unet = VideoUNet(cfg)
    frames = cfg.num_frames
    return unet.init(
        jax.random.key(0),
        jnp.zeros((frames, 8, 8, cfg.base.in_channels)),
        jnp.zeros((frames,)),
        jnp.zeros((frames, 77, cfg.base.cross_attention_dim)),
    )["params"]


def _assert_trees_equal(a, b, path=""):
    assert isinstance(a, dict) == isinstance(b, dict), path
    if isinstance(a, dict):
        assert set(a) == set(b), f"{path}: {set(a) ^ set(b)}"
        for k in a:
            _assert_trees_equal(a[k], b[k], f"{path}/{k}")
    else:
        np.testing.assert_allclose(np.asarray(a, np.float32), b, rtol=1e-6,
                                   err_msg=path)


def test_video_unet_roundtrip_exact(video_params):
    spatial, motion = _synth(video_params)
    assert motion, "no motion-module keys synthesized"
    # real adapters ship exactly these shapes under temporal_transformer
    assert any(".temporal_transformer.proj_in.weight" in k for k in motion)
    assert any(".attn2." in k for k in motion), "motion blocks have 2 attns"
    converted = convert_video_unet(spatial, motion)
    _assert_trees_equal(
        converted,
        jax.tree_util.tree_map(lambda x: np.asarray(x), video_params),
    )


def test_motion_adapter_alone_covers_all_motion_modules(video_params):
    _, motion = _synth(video_params)
    converted = convert_motion_adapter(motion)
    expected = {
        k: v for k, v in video_params.items() if "motion_modules" in k
    }
    _assert_trees_equal(
        converted, jax.tree_util.tree_map(lambda x: np.asarray(x), expected)
    )


def test_sinusoidal_pe_interleaves():
    from chiaswarm_tpu.models.video_unet import _sinusoidal_pe

    pe = np.asarray(_sinusoidal_pe(8, 16, np.float32))
    # position 0: sin(0)=0 at even dims, cos(0)=1 at odd dims
    np.testing.assert_allclose(pe[0, 0::2], 0.0, atol=1e-7)
    np.testing.assert_allclose(pe[0, 1::2], 1.0, atol=1e-7)
    # interleaved layout: pe[p, 0] = sin(p), pe[p, 1] = cos(p)
    np.testing.assert_allclose(pe[3, 0], np.sin(3.0), atol=1e-6)
    np.testing.assert_allclose(pe[3, 1], np.cos(3.0), atol=1e-6)


def test_motion_module_torch_parity():
    """The AnimateDiff temporal transformer numerically validated against
    an exact-key torch mirror (roundtrip-only until now — VERDICT r03
    item 5): interleaved sinusoidal positions on the normed attention
    inputs, GEGLU FF, zero-init residual projection wiring."""
    import os
    import sys

    import numpy as np
    import torch

    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(__file__))
    from torch_unet_ref import MotionModuleT

    from chiaswarm_tpu.models.conversion import convert_state_dict
    from chiaswarm_tpu.models.video_unet import TemporalTransformer

    channels, heads, layers, frames = 32, 4, 2, 8
    torch.manual_seed(100)
    tref = MotionModuleT(channels, heads, layers).eval()
    state = {
        k.replace("temporal_transformer.", ""): v.numpy()
        for k, v in tref.state_dict().items()
    }

    def rename(name):
        name = name.replace(".to_out.0.", ".to_out_0.")
        name = name.replace(".ff.net.0.", ".ff.net_0.")
        name = name.replace(".ff.net.2.", ".ff.net_2.")
        return name

    params = convert_state_dict(state, rename)

    rng = np.random.default_rng(101)
    x = rng.standard_normal((frames, 6, 5, channels)).astype(np.float32)
    with torch.no_grad():
        out_t = tref(
            torch.from_numpy(x.transpose(0, 3, 1, 2)), frames
        ).numpy().transpose(0, 2, 3, 1)
    out_f = np.asarray(
        TemporalTransformer(channels, heads, layers).apply(
            {"params": params}, jnp.asarray(x), frames
        )
    )
    np.testing.assert_allclose(out_f, out_t, atol=3e-4, rtol=1e-3)
