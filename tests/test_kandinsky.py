"""Kandinsky 2.x cascade: prior embedding diffusion + image-embed decoder.

Covers VERDICT missing #2 (Kandinsky prior/decoder): KandinskyV22Pipeline
wire names resolve and produce images on tiny configs, with the prior
running as the internal prepipeline stage (reference
swarm/diffusion/pipeline_steps.py:7-38 semantics).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from chiaswarm_tpu import registry
from chiaswarm_tpu.models.prior import TINY_PRIOR, DiffusionPrior
from chiaswarm_tpu.pipelines.kandinsky import (
    KandinskyPipeline,
    KandinskyPriorPipeline,
    _prior_name_for,
)
from chiaswarm_tpu.weights import MissingWeightsError


def test_prior_model_forward():
    model = DiffusionPrior(TINY_PRIOR)
    cfg = TINY_PRIOR
    b = 2
    args = (
        jnp.zeros((b, cfg.embed_dim)),
        jnp.ones((b,)),
        jnp.zeros((b, cfg.text_seq, cfg.text_dim)),
        jnp.zeros((b, cfg.text_dim)),
    )
    params = model.init(jax.random.key(0), *args)
    out = model.apply(params, *args)
    assert out.shape == (b, cfg.embed_dim)
    assert np.isfinite(np.asarray(out)).all()


@pytest.fixture(scope="module")
def tiny_prior():
    return KandinskyPriorPipeline("test/tiny-kandinsky-prior")


@pytest.fixture(scope="module")
def tiny_decoder():
    return KandinskyPipeline("test/tiny-kandinsky")


def test_prior_generates_embeds(tiny_prior):
    embeds, neg = tiny_prior.generate(
        "a red fox", num_images=2, steps=3, rng=jax.random.key(0)
    )
    assert embeds.shape == (2, TINY_PRIOR.embed_dim)
    assert neg.shape == (2, TINY_PRIOR.embed_dim)
    assert not np.allclose(np.asarray(embeds), np.asarray(neg))


def test_prior_deterministic(tiny_prior):
    gen = lambda: np.asarray(
        tiny_prior.generate("same", steps=2, rng=jax.random.key(3))[0]
    )
    np.testing.assert_array_equal(gen(), gen())


def test_decoder_from_explicit_embeds(tiny_decoder):
    embeds = np.random.default_rng(0).standard_normal(
        (1, TINY_PRIOR.embed_dim)
    ).astype(np.float32)
    images, config = tiny_decoder.run(
        image_embeds=embeds, height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert images[0].size == (64, 64)
    assert "prior_s" not in config["timings"]  # prior stage skipped


def test_full_cascade_txt2img(tiny_decoder):
    images, config = tiny_decoder.run(
        prompt="a fox in the snow",
        height=64,
        width=64,
        num_inference_steps=2,
        prior_timesteps=2,
        rng=jax.random.key(0),
    )
    assert images[0].size == (64, 64)
    assert config["timings"]["prior_s"] > 0  # prior prepipeline ran
    assert config["timings"]["denoise_decode_s"] > 0


def test_embeds_condition_the_decoder(tiny_decoder):
    rng = np.random.default_rng(1)
    kw = dict(height=64, width=64, num_inference_steps=2, rng=jax.random.key(7))
    a = np.asarray(tiny_decoder.run(
        image_embeds=rng.standard_normal((1, TINY_PRIOR.embed_dim),
                                         ).astype(np.float32), **kw)[0][0])
    b = np.asarray(tiny_decoder.run(
        image_embeds=rng.standard_normal((1, TINY_PRIOR.embed_dim),
                                         ).astype(np.float32), **kw)[0][0])
    assert not np.array_equal(a, b)


def test_decoder_batch_follows_embeds(tiny_decoder):
    embeds = np.random.default_rng(2).standard_normal(
        (3, TINY_PRIOR.embed_dim)
    ).astype(np.float32)
    images, _ = tiny_decoder.run(
        image_embeds=embeds, height=64, width=64, num_inference_steps=2,
        rng=jax.random.key(0),
    )
    assert len(images) == 3  # batch from embeds, not num_images_per_prompt


def test_prior_typed_job_is_clean_error(tiny_prior):
    with pytest.raises(Exception, match="prepipeline stage"):
        tiny_prior.run(prompt="x")


def test_hint_on_non_controlnet_model_rejected(tiny_decoder):
    # a hint against a plain decoder checkpoint cannot condition anything
    with pytest.raises(Exception, match="not a ControlNet checkpoint"):
        tiny_decoder.run(
            prompt="x", pipeline_type="KandinskyV22ControlnetPipeline",
            hint=np.zeros((1, 8, 8, 3), np.float32), num_inference_steps=2,
        )


@pytest.fixture(scope="module")
def tiny_controlnet():
    return KandinskyPipeline("test/tiny-kandinsky-controlnet")


def test_controlnet_depth_hint_conditions(tiny_controlnet):
    """KandinskyV22ControlnetPipeline with a depth hint (reference
    job_arguments.py:386-388 passes `hint` instead of `image`)."""
    rng = np.random.default_rng(0)
    kw = dict(
        prompt="a robot, 4k photo",
        pipeline_type="KandinskyV22ControlnetPipeline",
        height=64, width=64, num_inference_steps=2, prior_timesteps=2,
        rng=jax.random.key(3),
    )
    a_hint = rng.random((64, 64, 3)).astype(np.float32)
    b_hint = rng.random((64, 64, 3)).astype(np.float32)
    a, cfg = tiny_controlnet.run(hint=a_hint, **kw)
    assert cfg["mode"] == "controlnet"
    assert a[0].size == (64, 64)
    b, _ = tiny_controlnet.run(hint=b_hint, **kw)
    assert not np.array_equal(np.asarray(a[0]), np.asarray(b[0]))


def test_controlnet_requires_hint(tiny_controlnet):
    with pytest.raises(Exception, match="requires a depth hint"):
        tiny_controlnet.run(prompt="x", num_inference_steps=2)


def test_registry_wire_names():
    pipe = registry.get_pipeline("test/tiny-kandinsky", "KandinskyV22Pipeline")
    assert isinstance(pipe, KandinskyPipeline)
    prior = registry.get_pipeline(
        "test/tiny-kandinsky-prior", "KandinskyV22PriorPipeline"
    )
    assert isinstance(prior, KandinskyPriorPipeline)


def test_prior_name_mapping():
    assert _prior_name_for("test/tiny-kandinsky") == "test/tiny-kandinsky-prior"
    assert (
        _prior_name_for("kandinsky-community/kandinsky-2-2-decoder")
        == "kandinsky-community/kandinsky-2-2-prior"
    )
    assert (
        _prior_name_for("kandinsky-community/kandinsky-2-1")
        == "kandinsky-community/kandinsky-2-1-prior"
    )


def test_real_kandinsky_requires_weights(sdaas_root):
    with pytest.raises(MissingWeightsError, match="Kandinsky"):
        KandinskyPipeline("kandinsky-community/kandinsky-2-2-decoder")


def test_kandinsky_job_through_callback():
    from chiaswarm_tpu.workflows.diffusion import diffusion_callback

    artifacts, config = diffusion_callback(
        "cpu:0",
        "kandinsky-community/kandinsky-2-2-decoder",
        pipeline_type="KandinskyV22Pipeline",
        prompt="wire",
        height=64,
        width=64,
        num_inference_steps=2,
        prior_timesteps=2,
        test_tiny_model=True,
        rng=jax.random.key(0),
    )
    assert config["model"] == "test/tiny-kandinsky"
    assert artifacts["primary"]["content_type"] == "image/jpeg"


def test_img2img_conditions_on_init_image(tiny_decoder):
    """Kandinsky img2img (reference swarm/test.py:100-113 schedules it via
    AutoPipelineForImage2Image): the init image sets the denoise start."""
    from PIL import Image as PILImage

    rng = np.random.default_rng(0)
    img_a = PILImage.fromarray((rng.random((64, 64, 3)) * 255).astype(np.uint8))
    img_b = PILImage.fromarray((rng.random((64, 64, 3)) * 255).astype(np.uint8))
    kw = dict(prompt="repaint", num_inference_steps=4, prior_timesteps=2,
              strength=0.5, rng=jax.random.key(9))
    a, cfg = tiny_decoder.run(image=img_a, **kw)
    assert cfg["mode"] == "img2img"
    assert a[0].size == (64, 64)
    b, _ = tiny_decoder.run(image=img_b, **kw)
    assert not np.array_equal(np.asarray(a[0]), np.asarray(b[0]))
