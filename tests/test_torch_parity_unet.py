"""Numeric UNet/VAE conversion validation against an in-repo torch
reference (VERDICT r2 missing #2: the flagship conversion was only ever
shape-checked — diffusers is not installed here, so tests/torch_unet_ref.py
reproduces its graph + key layout and gives the converter a ground truth).

What a pass proves: the diffusers-layout state dict, converted through
models/conversion.py, drives the flax UNet/VAE to the SAME outputs the
torch graph computes — renames, transposes (conv OIHW->HWIO, 1x1-conv
projections -> Dense), norm epsilons, GEGLU/silu activations, skip wiring,
and the SDXL addition-embed branch all agree numerically.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

torch = pytest.importorskip("torch")

from chiaswarm_tpu.models import configs as cfgs  # noqa: E402
from chiaswarm_tpu.models.conversion import convert_unet, convert_vae  # noqa: E402
from chiaswarm_tpu.models.unet2d import UNet2DConditionModel  # noqa: E402
from chiaswarm_tpu.models.vae import AutoencoderKL  # noqa: E402

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
from torch_unet_ref import AutoencoderKLT, UNet2DConditionT  # noqa: E402


def _to_torch_nchw(x):
    return torch.from_numpy(np.asarray(x).transpose(0, 3, 1, 2))


class TestUNetTorchParity:
    def _compare(self, cfg, added=None):
        torch.manual_seed(0)
        tref = UNet2DConditionT(cfg).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        params = convert_unet(state)

        rng = np.random.default_rng(1)
        x = rng.standard_normal((2, 16, 16, cfg.in_channels)).astype(np.float32)
        t = np.array([7.0, 451.0], np.float32)
        ctx = rng.standard_normal((2, 77, cfg.cross_attention_dim)).astype(
            np.float32
        )
        t_added = None
        if added is not None:
            t_added = {
                "text_embeds": torch.from_numpy(added["text_embeds"]),
                "time_ids": torch.from_numpy(added["time_ids"]),
            }
        with torch.no_grad():
            out_t = tref(
                _to_torch_nchw(x), torch.from_numpy(t),
                torch.from_numpy(ctx), t_added,
            ).numpy().transpose(0, 2, 3, 1)

        flax_unet = UNet2DConditionModel(cfg)
        kwargs = {}
        if added is not None:
            kwargs["added_cond"] = {
                "text_embeds": jnp.asarray(added["text_embeds"]),
                "time_ids": jnp.asarray(added["time_ids"]),
            }
        out_f = np.asarray(
            flax_unet.apply(
                {"params": params}, jnp.asarray(x), jnp.asarray(t),
                jnp.asarray(ctx), **kwargs,
            )
        )
        np.testing.assert_allclose(out_f, out_t, atol=2e-4, rtol=1e-3)

    def test_sd_unet_matches(self):
        self._compare(cfgs.TINY_UNET)

    def test_xl_unet_matches(self):
        cfg = cfgs.TINY_XL_UNET
        rng = np.random.default_rng(2)
        pooled_dim = cfg.addition_embed_dim - 6 * cfg.addition_time_embed_dim
        added = {
            "text_embeds": rng.standard_normal((2, pooled_dim)).astype(
                np.float32
            ),
            "time_ids": np.asarray(
                [[64, 64, 0, 0, 64, 64]] * 2, np.float32
            ),
        }
        self._compare(cfg, added=added)

    def test_audioldm_unet_matches(self):
        """AudioLDM branch: `simple_projection` class embedding concatenated
        to temb, transformer blocks self-attending (encoder_hidden_states=
        None) — the graph diffusers runs for cvssp/audioldm-*
        (reference swarm/audio/audioldm.py:19)."""
        import dataclasses

        cfg = dataclasses.replace(
            cfgs.TINY_UNET,
            in_channels=8, out_channels=8,
            cross_attention_dim=0,
            class_embed_dim=16,
            class_embeddings_concat=True,
        )
        torch.manual_seed(6)
        tref = UNet2DConditionT(cfg).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        params = convert_unet(state)

        rng = np.random.default_rng(7)
        x = rng.standard_normal((2, 16, 16, 8)).astype(np.float32)
        t = np.array([7.0, 451.0], np.float32)
        labels = rng.standard_normal((2, 16)).astype(np.float32)
        with torch.no_grad():
            out_t = tref(
                _to_torch_nchw(x), torch.from_numpy(t), None,
                class_labels=torch.from_numpy(labels),
            ).numpy().transpose(0, 2, 3, 1)
        out_f = np.asarray(
            UNet2DConditionModel(cfg).apply(
                {"params": params}, jnp.asarray(x), jnp.asarray(t), None,
                class_labels=jnp.asarray(labels),
            )
        )
        np.testing.assert_allclose(out_f, out_t, atol=2e-4, rtol=1e-3)

    def test_audioldm_config_inference_roundtrip(self):
        """infer_unet2d_config recovers the full geometry from the state
        dict alone (class embed + concat + self-attn + channels)."""
        import dataclasses

        from chiaswarm_tpu.models.conversion import infer_unet2d_config

        cfg = dataclasses.replace(
            cfgs.TINY_UNET,
            in_channels=8, out_channels=8,
            cross_attention_dim=0,
            class_embed_dim=16,
            class_embeddings_concat=True,
            num_attention_heads=4,
        )
        torch.manual_seed(8)
        state = {
            k: v.numpy()
            for k, v in UNet2DConditionT(cfg).state_dict().items()
        }
        inferred = infer_unet2d_config(
            state, {"attention_head_dim": 4}
        )
        assert inferred == cfg


class TestVAETorchParity:
    @pytest.fixture(scope="class")
    def pair(self):
        torch.manual_seed(3)
        tref = AutoencoderKLT(cfgs.TINY_VAE).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        params = convert_vae(state)
        return tref, params

    def test_encode_matches(self, pair):
        tref, params = pair
        vae = AutoencoderKL(cfgs.TINY_VAE)
        rng = np.random.default_rng(4)
        px = rng.standard_normal((1, 32, 32, 3)).astype(np.float32)
        with torch.no_grad():
            mean_t = tref.encode_mode(_to_torch_nchw(px)).numpy().transpose(
                0, 2, 3, 1
            )
        # our encode returns the scaled mode; unscale for comparison
        z_f = np.asarray(
            vae.apply({"params": params}, jnp.asarray(px), method=vae.encode)
        ) / cfgs.TINY_VAE.scaling_factor
        np.testing.assert_allclose(z_f, mean_t, atol=2e-4, rtol=1e-3)

    def test_decode_matches(self, pair):
        tref, params = pair
        vae = AutoencoderKL(cfgs.TINY_VAE)
        rng = np.random.default_rng(5)
        z = rng.standard_normal(
            (1, 16, 16, cfgs.TINY_VAE.latent_channels)
        ).astype(np.float32)
        with torch.no_grad():
            px_t = tref.decode_raw(_to_torch_nchw(z)).numpy().transpose(
                0, 2, 3, 1
            )
        px_f = np.asarray(
            vae.apply(
                {"params": params},
                jnp.asarray(z) * cfgs.TINY_VAE.scaling_factor,
                method=vae.decode,
            )
        )
        np.testing.assert_allclose(px_f, px_t, atol=2e-4, rtol=1e-3)

    def test_audioldm_vae_matches_and_infers(self):
        """Mel-spectrogram VAE (1 input channel, 8 latent channels) decodes
        identically, and infer_vae_config recovers the geometry."""
        import dataclasses

        from chiaswarm_tpu.models.conversion import infer_vae_config

        cfg = dataclasses.replace(
            cfgs.TINY_VAE, in_channels=1, latent_channels=8,
            scaling_factor=0.9227,
        )
        torch.manual_seed(9)
        tref = AutoencoderKLT(cfg).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        assert infer_vae_config(state, {"scaling_factor": 0.9227}) == cfg
        params = convert_vae(state)
        vae = AutoencoderKL(cfg)
        rng = np.random.default_rng(10)
        px = rng.standard_normal((1, 32, 16, 1)).astype(np.float32)
        with torch.no_grad():
            mean_t = tref.encode_mode(_to_torch_nchw(px)).numpy().transpose(
                0, 2, 3, 1
            )
        z_f = np.asarray(
            vae.apply({"params": params}, jnp.asarray(px), method=vae.encode)
        ) / cfg.scaling_factor
        np.testing.assert_allclose(z_f, mean_t, atol=2e-4, rtol=1e-3)


class TestK22UNetTorchParity:
    """The K-block UNet numerically validated the same way the SD family
    is: a torch mirror with exact diffusers key names feeds
    convert_kandinsky_unet, and both sides must compute identical outputs
    (scale_shift resnets, resnet samplers, added-KV attention, image
    conditioning branches). This covers the Kandinsky 2.2 image/silu path;
    the IF text/gelu variants share these exact blocks but are pinned by
    roundtrip tests only (the torch mirror has no text-conditioning
    branch yet)."""

    def test_k22_unet_matches(self):
        from torch_unet_ref import K22UNetT

        from chiaswarm_tpu.models.conversion import convert_kandinsky_unet
        from chiaswarm_tpu.models.unet_kandinsky import TINY_K22_UNET, K22UNet

        cfg = TINY_K22_UNET
        torch.manual_seed(6)
        tref = K22UNetT(cfg).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        inferred, params = convert_kandinsky_unet(
            state, {"attention_head_dim": cfg.attention_head_dim,
                    "norm_num_groups": cfg.norm_num_groups},
        )
        assert inferred == cfg

        rng = np.random.default_rng(7)
        x = rng.standard_normal((2, 16, 16, cfg.in_channels)).astype(np.float32)
        t = np.array([11.0, 333.0], np.float32)
        emb = rng.standard_normal((2, cfg.encoder_hid_dim)).astype(np.float32)
        with torch.no_grad():
            out_t = tref(
                _to_torch_nchw(x), torch.from_numpy(t), torch.from_numpy(emb)
            ).numpy().transpose(0, 2, 3, 1)
        out_f = np.asarray(
            K22UNet(cfg).apply(
                {"params": params}, jnp.asarray(x), jnp.asarray(t),
                jnp.asarray(emb),
            )
        )
        np.testing.assert_allclose(out_f, out_t, atol=2e-4, rtol=1e-3)

    def test_k21_text_image_unet_matches(self):
        """Kandinsky 2.1: TextImageTimeEmbedding + TextImageProjection
        conditioning over the same K blocks — torch-mirror numeric parity
        + exact config inference (reference swarm/test.py:85-107)."""
        import dataclasses

        from torch_unet_ref import K22UNetT

        from chiaswarm_tpu.models.conversion import convert_kandinsky_unet
        from chiaswarm_tpu.models.unet_kandinsky import TINY_K22_UNET, K22UNet

        # real K2.1 geometry relations: image embeds and pooled text embeds
        # are cross_attention_dim wide; text states are encoder_hid wide
        cfg = dataclasses.replace(
            TINY_K22_UNET, conditioning="text_image",
            encoder_hid_dim=24, image_embed_dim=TINY_K22_UNET.cross_attention_dim,
            image_proj_tokens=3,
        )
        torch.manual_seed(12)
        tref = K22UNetT(cfg).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        inferred, params = convert_kandinsky_unet(
            state, {"attention_head_dim": cfg.attention_head_dim,
                    "norm_num_groups": cfg.norm_num_groups},
        )
        assert inferred == cfg

        rng = np.random.default_rng(13)
        x = rng.standard_normal((2, 16, 16, cfg.in_channels)).astype(np.float32)
        t = np.array([11.0, 333.0], np.float32)
        image_embeds = rng.standard_normal(
            (2, cfg.image_embed_dim)).astype(np.float32)
        text_states = rng.standard_normal((2, 7, 24)).astype(np.float32)
        text_embeds = rng.standard_normal(
            (2, cfg.cross_attention_dim)).astype(np.float32)
        with torch.no_grad():
            out_t = tref(
                _to_torch_nchw(x), torch.from_numpy(t),
                torch.from_numpy(image_embeds),
                text_states=torch.from_numpy(text_states),
                text_embeds=torch.from_numpy(text_embeds),
            ).numpy().transpose(0, 2, 3, 1)
        out_f = np.asarray(
            K22UNet(cfg).apply(
                {"params": params}, jnp.asarray(x), jnp.asarray(t),
                {"text_states": jnp.asarray(text_states),
                 "text_embeds": jnp.asarray(text_embeds),
                 "image_embeds": jnp.asarray(image_embeds)},
            )
        )
        np.testing.assert_allclose(out_f, out_t, atol=2e-4, rtol=1e-3)


class TestIFUNetTorchParity:
    """DeepFloyd IF's text-conditioning branch numerically validated (the
    torch mirror was roundtrip-only here until now — VERDICT r03 item 5):
    TextTimeEmbedding attention pooling, gelu K-blocks, the SR stage's
    class-embedded noise level."""

    def _run(self, cfg, class_labels=None):
        from torch_unet_ref import K22UNetT

        from chiaswarm_tpu.models.conversion import convert_kandinsky_unet
        from chiaswarm_tpu.models.unet_kandinsky import K22UNet

        torch.manual_seed(70)
        tref = K22UNetT(cfg).eval()
        state = {k: v.numpy() for k, v in tref.state_dict().items()}
        inferred, params = convert_kandinsky_unet(
            state, {"attention_head_dim": cfg.attention_head_dim,
                    "norm_num_groups": cfg.norm_num_groups,
                    "act_fn": cfg.act,
                    "addition_embed_type_num_heads": cfg.addition_embed_heads},
        )
        assert inferred == cfg

        rng = np.random.default_rng(71)
        x = rng.standard_normal((2, 16, 16, cfg.in_channels)).astype(np.float32)
        t = np.array([3.0, 801.0], np.float32)
        states = rng.standard_normal((2, 6, cfg.encoder_hid_dim)).astype(
            np.float32
        )
        kw_t = {}
        kw_f = {}
        if class_labels is not None:
            kw_t["class_labels"] = torch.from_numpy(class_labels)
            kw_f["class_labels"] = jnp.asarray(class_labels)
        with torch.no_grad():
            out_t = tref(
                _to_torch_nchw(x), torch.from_numpy(t),
                torch.from_numpy(states), **kw_t,
            ).numpy().transpose(0, 2, 3, 1)
        out_f = np.asarray(
            K22UNet(cfg).apply(
                {"params": params}, jnp.asarray(x), jnp.asarray(t),
                jnp.asarray(states), **kw_f,
            )
        )
        np.testing.assert_allclose(out_f, out_t, atol=3e-4, rtol=1e-3)

    def test_if_base_text_conditioning_matches(self):
        from chiaswarm_tpu.models.unet_kandinsky import TINY_IF_UNET

        self._run(TINY_IF_UNET)

    def test_if_sr_class_embed_matches(self):
        from chiaswarm_tpu.models.unet_kandinsky import TINY_IF_SR_UNET

        self._run(
            TINY_IF_SR_UNET,
            class_labels=np.array([50.0, 250.0], np.float32),
        )
