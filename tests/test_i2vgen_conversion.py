"""I2VGenXL conversion contract (VERDICT r03 missing #1: the reference's
DEFAULT img2vid pipeline type, swarm/job_arguments.py:143).

The checkpoint side is a torch mirror with exact diffusers key names
(trunk pieces shared with test_unet3d_conversion's UNet3DT components):
random torch init -> state dict -> convert -> flax forward must equal the
torch forward, covering the FPS embedding, the image-latents projection +
frame-axis temporal encoder, the three-source context assembly (text +
adaptive-pooled first-frame grid + lifted image embedding), and the
shared 3D trunk.
"""

import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(__file__))

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402

from test_unet3d_conversion import UNet3DT  # noqa: E402
from torch_unet_ref import TimestepEmbeddingT, timestep_embedding_t  # noqa: E402

from chiaswarm_tpu.models.conversion import (  # noqa: E402
    convert_i2vgen_unet,
    infer_i2vgen_config,
)
from chiaswarm_tpu.models.i2vgen import (  # noqa: E402
    TINY_I2VGEN,
    I2VGenXLUNet,
)


class _GELUProj(nn.Module):
    def __init__(self, dim, inner):
        super().__init__()
        self.proj = nn.Linear(dim, inner)

    def forward(self, x):
        return F.gelu(self.proj(x))


class _TemporalEncoderT(nn.Module):
    """I2VGenXLTransformerTemporalEncoder with exact diffusers keys."""

    def __init__(self, dim, heads=2):
        super().__init__()
        self.heads = heads
        self.head_dim = dim // heads
        self.norm1 = nn.LayerNorm(dim)
        attn = nn.Module()
        attn.to_q = nn.Linear(dim, dim, bias=False)
        attn.to_k = nn.Linear(dim, dim, bias=False)
        attn.to_v = nn.Linear(dim, dim, bias=False)
        attn.to_out = nn.ModuleList([nn.Linear(dim, dim)])
        self.attn1 = attn
        ff = nn.Module()
        ff.net = nn.ModuleList([_GELUProj(dim, 4 * dim), nn.Identity(),
                                nn.Linear(4 * dim, dim)])
        self.ff = ff

    def forward(self, tokens):
        b, f, d = tokens.shape
        h = self.norm1(tokens)
        q = self.attn1.to_q(h).view(b, f, self.heads, self.head_dim)
        k = self.attn1.to_k(h).view(b, f, self.heads, self.head_dim)
        v = self.attn1.to_v(h).view(b, f, self.heads, self.head_dim)
        q, k, v = (t.transpose(1, 2) for t in (q, k, v))
        attn = (q @ k.transpose(-1, -2) * self.head_dim ** -0.5).softmax(-1) @ v
        attn = attn.transpose(1, 2).reshape(b, f, d)
        tokens = tokens + self.attn1.to_out[0](attn)
        return tokens + self.ff.net[2](self.ff.net[0](tokens))


class I2VGenXLUNetT(UNet3DT):
    """Exact-key diffusers I2VGenXLUNet mirror: the UNet3DT trunk (8-ch
    conv_in) plus the I2VGen conditioning modules."""

    def __init__(self, cfg):
        super().__init__(cfg.trunk())
        c0 = cfg.block_out_channels[0]
        temb_dim = 4 * c0
        cross = cfg.cross_attention_dim
        ic = cfg.in_channels
        self.i2v_cfg = cfg
        self.fps_embedding = TimestepEmbeddingT(c0, temb_dim)
        self.image_latents_proj_in = nn.Sequential(
            nn.Conv2d(ic, 4 * ic, 1), nn.SiLU(),
            nn.Conv2d(4 * ic, 4 * ic, 3, padding=1), nn.SiLU(),
            nn.Conv2d(4 * ic, ic, 3, padding=1),
        )
        self.image_latents_temporal_encoder = _TemporalEncoderT(ic)
        self.image_latents_context_embedding = nn.Sequential(
            nn.Conv2d(ic, 8 * ic, 3, padding=1), nn.SiLU(),
            nn.AdaptiveAvgPool2d((32, 32)),
            nn.Conv2d(8 * ic, 16 * ic, 3, stride=2, padding=1), nn.SiLU(),
            nn.Conv2d(16 * ic, cross, 3, stride=2, padding=1),
        )
        self.context_embedding = nn.Sequential(
            nn.Linear(cross, temb_dim), nn.SiLU(),
            nn.Linear(temb_dim, ic * cross),
        )

    def forward(self, sample, timesteps, fps, image_latents,
                image_embeddings, encoder_hidden_states, num_frames):
        cfg = self.i2v_cfg
        c0 = cfg.block_out_channels[0]
        bf = sample.shape[0]
        b = bf // num_frames
        temb = self.time_embedding(timestep_embedding_t(timesteps, c0))
        temb = temb + self.fps_embedding(timestep_embedding_t(fps, c0))
        temb = temb.repeat_interleave(num_frames, dim=0)

        first = image_latents.view(b, num_frames, *image_latents.shape[1:])[
            :, 0
        ]
        y = self.image_latents_context_embedding(first)
        latent_tokens = y.flatten(2).permute(0, 2, 1)
        img = self.context_embedding(image_embeddings)
        img_tokens = img.view(b, cfg.in_channels, cfg.cross_attention_dim)
        ctx = torch.cat([encoder_hidden_states, latent_tokens, img_tokens],
                        dim=1)
        ctx = ctx.repeat_interleave(num_frames, dim=0)

        il = self.image_latents_proj_in(image_latents)
        _, c, h, w = il.shape
        tokens = il.view(b, num_frames, c, h * w).permute(0, 3, 1, 2)
        tokens = tokens.reshape(b * h * w, num_frames, c)
        tokens = self.image_latents_temporal_encoder(tokens)
        il = tokens.view(b, h * w, num_frames, c).permute(0, 2, 3, 1)
        il = il.reshape(bf, c, h, w)

        x = torch.cat([sample, il], dim=1)

        # the UNet3DT trunk, with temb/ctx precomputed
        x = self.conv_in(x)
        x = self.transformer_in(x, num_frames)
        skips = [x]
        for stage in self.down_blocks:
            for i, resnet in enumerate(stage.resnets):
                x = resnet(x, temb)
                x = stage.temp_convs[i](x, num_frames)
                if hasattr(stage, "attentions"):
                    x = stage.attentions[i](x, ctx)
                    x = stage.temp_attentions[i](x, num_frames)
                skips.append(x)
            if hasattr(stage, "downsamplers"):
                x = stage.downsamplers[0].conv(x)
                skips.append(x)
        m = self.mid_block
        x = m.resnets[0](x, temb)
        x = m.temp_convs[0](x, num_frames)
        x = m.attentions[0](x, ctx)
        x = m.temp_attentions[0](x, num_frames)
        x = m.resnets[1](x, temb)
        x = m.temp_convs[1](x, num_frames)
        for stage in self.up_blocks:
            for i, resnet in enumerate(stage.resnets):
                x = torch.cat([x, skips.pop()], dim=1)
                x = resnet(x, temb)
                x = stage.temp_convs[i](x, num_frames)
                if hasattr(stage, "attentions"):
                    x = stage.attentions[i](x, ctx)
                    x = stage.temp_attentions[i](x, num_frames)
            if hasattr(stage, "upsamplers"):
                x = F.interpolate(x, scale_factor=2.0, mode="nearest")
                x = stage.upsamplers[0].conv(x)
        return self.conv_out(F.silu(self.conv_norm_out(x)))


def _state_numpy(module) -> dict:
    return {k: v.detach().numpy() for k, v in module.state_dict().items()}


@pytest.fixture(scope="module")
def mirror():
    torch.manual_seed(80)
    m = I2VGenXLUNetT(TINY_I2VGEN)
    m.eval()
    return m


def test_i2vgen_config_inferred(mirror):
    cfg = infer_i2vgen_config(
        _state_numpy(mirror),
        {"attention_head_dim": TINY_I2VGEN.attention_head_dim,
         "norm_num_groups": TINY_I2VGEN.norm_num_groups},
    )
    assert cfg == TINY_I2VGEN


def test_i2vgen_torch_parity(mirror):
    params = convert_i2vgen_unet(_state_numpy(mirror))
    rng = np.random.default_rng(81)
    b, f, hw = 2, 3, 16
    cfg = TINY_I2VGEN
    sample = rng.standard_normal((b * f, hw, hw, 4)).astype(np.float32)
    t = np.asarray([2.0, 500.0], np.float32)
    fps = np.asarray([16.0, 16.0], np.float32)
    il = rng.standard_normal((b * f, hw, hw, 4)).astype(np.float32)
    emb = rng.standard_normal((b, cfg.cross_attention_dim)).astype(
        np.float32
    )
    ctx = rng.standard_normal((b, 5, cfg.cross_attention_dim)).astype(
        np.float32
    )

    def nchw(x):
        return torch.from_numpy(x).permute(0, 3, 1, 2)

    with torch.no_grad():
        out_t = mirror(
            nchw(sample), torch.from_numpy(t), torch.from_numpy(fps),
            nchw(il), torch.from_numpy(emb), torch.from_numpy(ctx), f,
        ).permute(0, 2, 3, 1).numpy()

    out_f = I2VGenXLUNet(cfg).apply(
        {"params": params}, jnp.asarray(sample), jnp.asarray(t),
        jnp.asarray(fps), jnp.asarray(il), jnp.asarray(emb),
        jnp.asarray(ctx), f,
    )
    np.testing.assert_allclose(np.asarray(out_f), out_t, atol=3e-4, rtol=1e-3)


def test_full_i2vgen_repo_check_and_pipeline(sdaas_root, tmp_path):
    """A complete synthetic i2vgen-xl repo — torch-mirror UNet + VAE, REAL
    transformers CLIP text/vision state dicts — passes `initialize
    --check` AND serves an img2vid job end-to-end with converted weights
    (the reference's default img2vid path, swarm/job_arguments.py:143)."""
    import json

    from PIL import Image
    from safetensors.numpy import save_file
    from transformers import (
        CLIPTextConfig as HFCLIPTextConfig,
        CLIPTextModel,
        CLIPVisionConfig as HFCLIPVisionConfig,
        CLIPVisionModelWithProjection,
    )

    from torch_unet_ref import AutoencoderKLT

    from chiaswarm_tpu.initialize import verify_local_model
    from chiaswarm_tpu.models import configs as cfgs
    from chiaswarm_tpu.pipelines.video import run_img2vid
    from chiaswarm_tpu.settings import Settings, save_settings

    name = "ali-vilab/i2vgen-xl"
    root = tmp_path / "models"
    save_settings(Settings(model_root_dir=str(root)))
    repo = root / name
    torch.manual_seed(82)

    (repo / "unet").mkdir(parents=True)
    save_file(
        _state_numpy(I2VGenXLUNetT(TINY_I2VGEN)),
        str(repo / "unet" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "unet" / "config.json").write_text(json.dumps({
        "attention_head_dim": TINY_I2VGEN.attention_head_dim,
        "norm_num_groups": TINY_I2VGEN.norm_num_groups,
    }))

    text = CLIPTextModel(HFCLIPTextConfig(
        vocab_size=1000, hidden_size=16, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=64,
        max_position_embeddings=77, hidden_act="gelu",
    ))
    (repo / "text_encoder").mkdir(parents=True)
    save_file(
        {k: v.numpy() for k, v in text.state_dict().items()},
        str(repo / "text_encoder" / "model.safetensors"),
    )
    (repo / "text_encoder" / "config.json").write_text(json.dumps({
        "vocab_size": 1000, "hidden_size": 16, "num_hidden_layers": 2,
        "num_attention_heads": 2, "hidden_act": "gelu",
    }))

    vision = CLIPVisionModelWithProjection(HFCLIPVisionConfig(
        image_size=32, patch_size=8, hidden_size=24, num_hidden_layers=2,
        num_attention_heads=2, intermediate_size=96,
        projection_dim=TINY_I2VGEN.cross_attention_dim,
        hidden_act="quick_gelu",
    ))
    (repo / "image_encoder").mkdir(parents=True)
    save_file(
        {k: v.numpy() for k, v in vision.state_dict().items()},
        str(repo / "image_encoder" / "model.safetensors"),
    )
    (repo / "image_encoder" / "config.json").write_text(json.dumps({
        "image_size": 32, "patch_size": 8, "hidden_size": 24,
        "num_hidden_layers": 2, "num_attention_heads": 2,
        "projection_dim": TINY_I2VGEN.cross_attention_dim,
        "hidden_act": "quick_gelu",
    }))

    vae = AutoencoderKLT(cfgs.TINY_VAE)
    (repo / "vae").mkdir(parents=True)
    save_file(
        _state_numpy(vae),
        str(repo / "vae" / "diffusion_pytorch_model.safetensors"),
    )
    (repo / "vae" / "config.json").write_text(json.dumps({
        "scaling_factor": 0.18215,
    }))

    report = verify_local_model(name, root)
    assert report is not None
    assert set(report) == {"unet", "text_encoder", "image_encoder", "vae"}

    start = Image.fromarray(
        (np.random.default_rng(83).random((64, 64, 3)) * 255).astype(
            np.uint8
        )
    )
    artifacts, config = run_img2vid(
        "cpu", name, image=start, prompt="a drifting boat",
        num_inference_steps=2, num_frames=3,
        rng=__import__("jax").random.key(84),
    )
    assert artifacts["primary"]["blob"]
    assert config["frames"] == 3
    assert config["pipeline"] == "I2VGenXLPipeline"
