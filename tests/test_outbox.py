"""Durable result outbox (outbox.py): the write-ahead delivery contract
in isolation — spool before upload, unlink only on ACK, park permanent
refusals aside, recover everything after a restart.
"""

import json

import pytest

from chiaswarm_tpu import outbox as outbox_mod
from chiaswarm_tpu.outbox import Outbox, backoff_delay


@pytest.fixture()
def box(tmp_path):
    return Outbox(tmp_path / "outbox", max_entries=3)


def test_spool_is_atomic_json_on_disk(box):
    entry = box.spool({"id": "job-1", "artifacts": {"primary": {}}})
    assert entry.path is not None and entry.path.is_file()
    assert not list(box.directory.glob("*.tmp"))  # tmp renamed away
    payload = json.loads(entry.path.read_text())
    assert payload["result"]["id"] == "job-1"
    assert box.depth == 1
    assert box.oldest_age_s() is not None and box.oldest_age_s() >= 0


def test_delivered_unlinks_only_that_entry(box):
    a = box.spool({"id": "a"})
    b = box.spool({"id": "b"})
    box.delivered(a)
    assert box.depth == 1
    assert not a.path.exists() and b.path.exists()


def test_recover_returns_entries_oldest_first(box):
    for i in range(3):
        box.spool({"id": f"job-{i}"})
    fresh = Outbox(box.directory)
    recovered = fresh.recover()
    assert [e.job_id for e in recovered] == ["job-0", "job-1", "job-2"]
    # recovery does not consume: the files stay until delivered()
    assert fresh.depth == 3


def test_park_keeps_the_envelope_on_disk_and_recoverable(box):
    entry = box.spool({"id": "refused"})
    box.park(entry)
    assert entry.parked and entry.path.name.endswith(".parked")
    assert box.depth == 1  # parked entries still count toward depth
    recovered = Outbox(box.directory).recover()
    assert [e.job_id for e in recovered] == ["refused"]
    assert recovered[0].parked


def test_unreadable_entry_is_skipped_not_fatal(box):
    box.spool({"id": "good"})
    (box.directory / "00000000000000000000-0000-corrupt.json").write_text("{nope")
    recovered = Outbox(box.directory).recover()
    assert [e.job_id for e in recovered] == ["good"]
    # the corrupt file is left in place for the operator
    assert box.depth == 2


def test_job_id_sanitized_in_filename(box):
    entry = box.spool({"id": "../../etc/passwd job\n1"})
    assert entry.path.parent == box.directory
    assert "/" not in entry.path.name.replace(box.directory.name, "")
    assert "\n" not in entry.path.name


def test_saturation_flag(box):
    assert not box.saturated
    for i in range(3):
        box.spool({"id": str(i)})
    assert box.saturated
    # saturation never blocks spooling — it is a health signal only
    box.spool({"id": "overflow"})
    assert box.depth == 4


def test_spool_failure_degrades_to_memory_entry(box, monkeypatch):
    import os

    def boom(*a, **k):
        raise OSError("disk full")

    monkeypatch.setattr(os, "replace", boom)
    entry = box.spool({"id": "job-x"})
    assert entry.path is None  # in-memory only, still deliverable
    # delivered()/park() on a memory-only entry must not raise
    box.park(entry)
    box.delivered(entry)


def test_backoff_caps_and_jitters(monkeypatch):
    monkeypatch.setattr(outbox_mod, "BACKOFF_BASE_S", 0.5)
    monkeypatch.setattr(outbox_mod, "BACKOFF_CAP_S", 4.0)
    for retries, ceiling in ((1, 0.5), (2, 1.0), (3, 2.0), (4, 4.0), (50, 4.0)):
        samples = [backoff_delay(retries) for _ in range(50)]
        assert all(ceiling / 2 <= s <= ceiling for s in samples)
    # jittered: a fleet must not retry in lockstep
    assert len({round(backoff_delay(4), 6) for _ in range(50)}) > 5
