"""Smoke + golden CLI harnesses (VERDICT r04 #3/#4): listing, tiny-mode
serving through the real format_args path, artifact saving, and the
golden record->check->mismatch cycle against a temp manifest."""

import asyncio
import json

import pytest


def test_smoke_list_prints_all_families(capsys):
    from chiaswarm_tpu.smoke import amain

    rc = asyncio.run(amain(["--list"]))
    assert rc == 0
    names = capsys.readouterr().out.split()
    for family in ("txt2img", "sdxl", "bark", "img2vid", "vid2vid",
                   "audioldm2", "kandinsky3", "flux", "stitch"):
        assert family in names
    assert len(names) >= 20


def test_smoke_rejects_unknown_family():
    from chiaswarm_tpu.smoke import amain

    with pytest.raises(SystemExit):
        asyncio.run(amain(["no-such-family"]))


def test_smoke_tiny_echo_stitch_saves_artifacts(tmp_path, sdaas_root):
    from chiaswarm_tpu.smoke import amain

    out = tmp_path / "artifacts"
    rc = asyncio.run(amain(["--tiny", "--out", str(out), "echo", "stitch"]))
    assert rc == 0
    saved = sorted(p.name for p in out.iterdir())
    assert any(n.startswith("echo.") for n in saved), saved
    assert any(n.startswith("stitch.") for n in saved), saved


def test_golden_record_check_mismatch_cycle(tmp_path, monkeypatch,
                                            sdaas_root):
    from chiaswarm_tpu.golden import amain

    manifest = tmp_path / "goldens" / "manifest.json"
    monkeypatch.setenv("CHIASWARM_GOLDEN_MANIFEST", str(manifest))

    # check before record -> NO RECORDED GOLDEN, nonzero
    assert asyncio.run(amain(["--check", "--tiny", "img2txt"])) == 1

    assert asyncio.run(amain(["--record", "--tiny", "img2txt"])) == 0
    data = json.loads(manifest.read_text())
    entry = data["tiers"]["tiny"]["img2txt"]
    assert entry["expected_sha256"]
    assert entry["job"]["seed"] == 31337
    # asset URIs normalized: no ephemeral localhost port committed
    assert "127.0.0.1" not in manifest.read_text()
    assert entry["recorded_env"]["backend"] == "cpu"

    # same machine, same seed -> deterministic pass
    assert asyncio.run(amain(["--check", "--tiny", "img2txt"])) == 0

    # corrupt the hash -> mismatch reported, nonzero
    entry["expected_sha256"] = {"primary": "0" * 64}
    manifest.write_text(json.dumps(data))
    assert asyncio.run(amain(["--check", "--tiny", "img2txt"])) == 1
