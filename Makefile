# Developer/CI entry points. The builder and every future PR run lint
# exactly the way tier-1 does (tests/test_lint.py wraps the same call).

PYTHON ?= python

.PHONY: lint lint-json test

lint:
	$(PYTHON) -m chiaswarm_tpu.lint

lint-json:
	$(PYTHON) -m chiaswarm_tpu.lint --json

# the tier-1 quick suite (ROADMAP "Tier-1 verify" is the canonical line)
test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -m 'not slow' \
	  --continue-on-collection-errors -p no:cacheprovider
