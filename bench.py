"""Benchmark harness: SDXL 1024^2 30-step txt2img, images/sec/chip.

The primary config from BASELINE.md (the reference publishes no numbers,
SURVEY §6). Run on TPU this measures the real flagship pipeline; on CPU it
falls back to the tiny model so the harness itself stays testable, and
labels the metric accordingly. Secondary rows (SD2.1-768, SDXL+ControlNet)
and a warm-compile probe ride the same JSON object; each is best-effort so
a failure there never loses the primary metric.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

`vs_baseline` compares against the ROOFLINE-HONEST target (see BASELINE.md
round-3 re-derivation): SDXL 1024^2 30-step CFG needs ~419 UNet TFLOP per
image, so one 197-TFLOP/s v5e chip is compute-bound at ~0.47 img/s at 100%
MFU — the target is 0.33 img/s/chip (~70% MFU), not the physically
unreachable 1.0 the round-1 BASELINE guessed.
"""

from __future__ import annotations

import json
import os
import sys
import time

TARGET_IMG_PER_SEC_PER_CHIP = 0.33  # ~70% UNet MFU on one v5e chip


def probe_tpu(timeout_s: float) -> str:
    """Check in a subprocess whether the TPU backend initialises at all.

    Returns "tpu" (TPU device present), "no-tpu" (clean init, CPU-only
    machine — don't bother retrying), or "error" (init crashed or hung —
    worth a retry).

    Round-1 failure modes: the TPU/axon plugin either raised UNAVAILABLE at
    `jax.default_backend()` (bench died rc=1) or hung indefinitely during
    init (multichip dryrun died rc=124).  A subprocess probe with a hard
    timeout guards against both without wedging the parent.
    """
    import subprocess

    code = (
        "import jax; ds = jax.devices(); "
        "print('PLATFORMS', sorted({d.platform for d in ds}))"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"tpu probe timed out after {timeout_s:.0f}s\n")
        return "hang"
    platforms = []
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORMS "):
            platforms = [p.strip("[]', ") for p in line[10:].split(",")]
    if out.returncode != 0 or not platforms:
        sys.stderr.write(
            f"tpu probe rc={out.returncode} out={out.stdout!r} "
            f"err tail={out.stderr[-300:]!r}\n"
        )
        return "error"
    return "tpu" if "tpu" in platforms else "no-tpu"


def init_backend():
    """Initialise the jax backend, surviving TPU-init failures and hangs.

    If the TPU cannot be brought up within the probe budget, fall back to
    the CPU backend so a (labelled) number is still produced instead of
    rc=1/rc=124 with no metric.
    """
    probe_budget = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))
    attempts = int(os.environ.get("BENCH_TPU_PROBE_ATTEMPTS", "3"))
    tpu_ok = False
    hangs = 0
    for attempt in range(attempts):
        status = probe_tpu(probe_budget)
        if status == "tpu":
            tpu_ok = True
        if status in ("tpu", "no-tpu"):
            break
        if status == "hang":
            # a HANGING relay (observed wedged for 8+ hours in round 4)
            # is not cured by retrying — two consecutive full-budget
            # hangs and we take the labelled CPU fallback instead of
            # starving the driver's bench budget (round-3 failure mode)
            hangs += 1
            if hangs >= 2:
                sys.stderr.write(
                    "tpu relay hangs persistently; giving up early\n"
                )
                break
        else:
            hangs = 0
        if attempt + 1 < attempts:
            # relay/plugin restarts have been observed to take minutes;
            # back off harder each retry (VERDICT r03 weak #1)
            time.sleep(30 * (attempt + 1))

    import jax

    if not tpu_ok:
        sys.stderr.write("TPU unavailable -> CPU fallback bench\n")
        jax.config.update("jax_platforms", "cpu")
    try:
        return jax.default_backend(), jax.devices()
    except Exception as e:
        print(
            json.dumps(
                {
                    "metric": "bench_backend_init_failed",
                    "value": 0.0,
                    "unit": "images/sec/chip",
                    "vs_baseline": 0.0,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        raise SystemExit(0)


def _enable_compile_cache() -> None:
    """Same persistent XLA cache the worker uses (worker.py) — the bench
    both exercises it (warm-compile probe) and leaves it populated."""
    try:
        import jax

        from chiaswarm_tpu.settings import load_settings

        cache_dir = os.path.expanduser(load_settings().compilation_cache_dir)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception as e:
        sys.stderr.write(f"compilation cache unavailable: {e}\n")


def main() -> None:
    backend, chips = init_backend()
    on_tpu = any(d.platform == "tpu" for d in chips)
    _enable_compile_cache()

    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline
    chipset = ChipSet(chips)

    if on_tpu:
        model, size, steps = "stabilityai/stable-diffusion-xl-base-1.0", 1024, 30
        batch_candidates = [int(os.environ.get("BENCH_BATCH", 0)) or 4, 2, 1]
    else:
        # the smoke row only proves the harness; 4 steps keep the CPU
        # fallback (and its CI contract test) fast
        model, size, steps = "test/tiny-sd", 64, 4
        batch_candidates = [4]

    # perf does not depend on weight values: converted weights load from the
    # model root when present, else the bench opts into random init (the
    # worker's serving path never does — weights.py policy)
    pipe = SDPipeline(model, chipset=chipset, allow_random_init=True)

    result = None
    for batch in batch_candidates:
        try:
            result = run_config(pipe, size, steps, batch)
            break
        except Exception as e:  # OOM on small chips -> retry smaller batch
            sys.stderr.write(f"batch={batch} failed: {type(e).__name__}: {e}\n")
    if result is None:
        raise SystemExit("all batch sizes failed")

    images_per_sec, p50_job_s, batch, extra = result
    per_chip = images_per_sec / len(chips)
    metric = (
        "sdxl_txt2img_1024_30step_images_per_sec_per_chip"
        if on_tpu
        else "tiny_txt2img_cpu_smoke_images_per_sec_per_chip"
    )
    out = {
        "metric": metric,
        "value": round(per_chip, 4),
        "unit": "images/sec/chip",
        "vs_baseline": round(per_chip / TARGET_IMG_PER_SEC_PER_CHIP, 4),
        "target_img_per_sec_per_chip": TARGET_IMG_PER_SEC_PER_CHIP,
        "p50_job_s": round(p50_job_s, 3),
        "batch": batch,
        "chips": len(chips),
        "backend": backend,
        "steps": steps,
        "size": 1024 if on_tpu else 64,
        **extra,
    }
    if not on_tpu:
        # never let a CPU smoke number pass silently for a TPU datum
        # (VERDICT r03: the artifact itself must say the TPU was missing)
        out["tpu_unavailable"] = True

    # BENCH_FORCE_SECONDARY exercises the warm-probe + secondary-row code
    # paths on CPU with tiny models (they had never executed before a TPU
    # run — VERDICT r03 weak #4); it is a CPU-only knob — on the TPU the
    # BENCH_CONFIGS primary/full split alone decides the budget
    tiny_secondary = (
        not on_tpu
        and os.environ.get("BENCH_FORCE_SECONDARY", "") not in ("", "0")
    )
    if on_tpu or tiny_secondary:
        out.update(_warm_compile_probe(pipe, size, steps, batch))
        full = os.environ.get("BENCH_CONFIGS", "full") == "full"
        if (on_tpu and full) or tiny_secondary:
            out.update(_secondary_rows(chipset, chips, pipe,
                                       tiny=not on_tpu))

    print(json.dumps(out))


def _warm_compile_probe(pipe, size, steps, batch) -> dict:
    """Prove the persistent compile cache: drop every in-memory executable,
    re-trace the SAME shape bucket, and time the rebuild — a worker restart
    pays this, not the cold compile (VERDICT weak #2)."""
    import jax

    try:
        jax.clear_caches()
        pipe._programs.clear()
        t0 = time.perf_counter()
        pipe.run(
            prompt="warm probe",
            height=size,
            width=size,
            num_inference_steps=steps,
            num_images_per_prompt=batch,
            scheduler_type="EulerDiscreteScheduler",
            rng=jax.random.key(99),
        )
        return {"warm_compile_s": round(time.perf_counter() - t0, 1)}
    except Exception as e:
        sys.stderr.write(f"warm-compile probe failed: {e}\n")
        # failure must be visible in the artifact, not just stderr
        return {"warm_compile_s": f"failed: {type(e).__name__}: {e}"}


def _secondary_rows(chipset, chips, xl_pipe, tiny: bool = False) -> dict:
    """SD2.1-768 and SDXL+ControlNet rows — regressions there were
    invisible when only the flagship config was measured (VERDICT weak #3).
    The ControlNet row reuses the resident SDXL pipeline (a second copy
    would double HBM); shorter runs keep the bench inside its budget.
    `tiny` swaps in the 64^2 test models so the whole code path executes
    hermetically on CPU."""
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    size = 64 if tiny else 1024
    steps = 2 if tiny else 30
    cn_name = (
        "test/tiny-controlnet" if tiny
        else "diffusers/controlnet-canny-sdxl-1.0"
    )
    sd21_name = "test/tiny-sd" if tiny else "stabilityai/stable-diffusion-2-1"
    sd21_size = 64 if tiny else 768
    out = {}
    try:
        from PIL import Image

        rate, p50 = _quick_rate(
            xl_pipe,
            dict(height=size, width=size, num_inference_steps=steps,
                 num_images_per_prompt=2,
                 controlnet_model_name=cn_name,
                 image=Image.new("RGB", (size, size), (128, 128, 128)),
                 scheduler_type="EulerDiscreteScheduler"),
        )
        row = "tiny_controlnet_smoke" if tiny else "sdxl_controlnet"
        out[f"{row}_img_per_sec_per_chip"] = round(rate / len(chips), 4)
        out[f"{row}_p50_job_s"] = round(p50, 3)
    except Exception as e:
        sys.stderr.write(f"controlnet row failed: {type(e).__name__}: {e}\n")
        row = "tiny_controlnet_smoke" if tiny else "sdxl_controlnet"
        out[f"{row}_row"] = f"failed: {type(e).__name__}: {e}"
    try:
        xl_pipe.release()  # free HBM before the second model family
        sd21 = SDPipeline(sd21_name, chipset=chipset, allow_random_init=True)
        rate, p50 = _quick_rate(
            sd21, dict(height=sd21_size, width=sd21_size,
                       num_inference_steps=steps,
                       num_images_per_prompt=4,
                       scheduler_type="EulerDiscreteScheduler")
        )
        row = "tiny_sd_smoke" if tiny else "sd21_768"
        out[f"{row}_img_per_sec_per_chip"] = round(rate / len(chips), 4)
        out[f"{row}_p50_job_s"] = round(p50, 3)
        sd21.release()
    except Exception as e:
        sys.stderr.write(f"sd21 row failed: {type(e).__name__}: {e}\n")
        row = "tiny_sd_smoke" if tiny else "sd21_768"
        out[f"{row}_row"] = f"failed: {type(e).__name__}: {e}"
    return out


def _quick_rate(pipe, kw) -> tuple[float, float]:
    import jax

    pipe.run(rng=jax.random.key(0), prompt="bench", **kw)  # compile
    times = []
    for i in range(3):
        t0 = time.perf_counter()
        pipe.run(rng=jax.random.key(i + 1), prompt="bench", **kw)
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[1]  # true median of 3
    return kw["num_images_per_prompt"] / p50, p50


# peak dense bf16 TFLOP/s per chip, by device kind prefix
_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5p": 459.0,
    "TPU v5": 459.0,
    "TPU v6 lite": 918.0,
}


def peak_tflops(device) -> float | None:
    override = os.environ.get("BENCH_PEAK_TFLOPS")
    if override:
        return float(override)
    kind = getattr(device, "device_kind", "")
    for prefix, tf in _PEAK_TFLOPS.items():
        if kind.startswith(prefix):
            return tf
    return None


def run_config(pipe, size: int, steps: int, batch: int):
    import jax

    kw = dict(
        prompt="a photograph of an astronaut riding a horse on mars",
        negative_prompt="blurry, low quality",
        height=size,
        width=size,
        num_inference_steps=steps,
        num_images_per_prompt=batch,
        scheduler_type="EulerDiscreteScheduler",
    )

    # warmup: compile + first run
    t0 = time.perf_counter()
    pipe.run(rng=jax.random.key(0), **kw)
    warmup_s = time.perf_counter() - t0
    sys.stderr.write(f"warmup (incl. compile): {warmup_s:.1f}s\n")

    job_times, denoise_times = [], []
    runs = 3
    config = {}
    for i in range(runs):
        t0 = time.perf_counter()
        _, config = pipe.run(rng=jax.random.key(i + 1), **kw)
        job_times.append(time.perf_counter() - t0)
        denoise_times.append(config["timings"]["denoise_decode_s"])
        sys.stderr.write(
            f"run {i}: {job_times[-1]:.2f}s job, "
            f"{denoise_times[-1]:.2f}s denoise+decode\n"
        )

    order = sorted(range(runs), key=lambda i: job_times[i])
    mid = order[runs // 2]
    p50 = job_times[mid]
    extra = {"denoise_fraction": round(denoise_times[mid] / p50, 3)}
    peak = peak_tflops(jax.devices()[0])
    if peak and config.get("unet_tflops"):
        # MFU over the denoise+decode program (UNet FLOPs only — VAE and
        # ControlNet are excluded, so this is a conservative floor). The
        # batch shards over the mesh, so peak scales with chip count.
        extra["unet_mfu"] = round(
            config["unet_tflops"]
            / denoise_times[mid]
            / (peak * len(jax.devices())),
            4,
        )
    return batch / p50, p50, batch, extra


if __name__ == "__main__":
    main()
