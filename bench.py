"""Benchmark harness: SDXL 1024^2 30-step txt2img, images/sec/chip.

The primary config from BASELINE.md (the reference publishes no numbers,
SURVEY §6). Run on TPU this measures the real flagship pipeline; on CPU it
falls back to the tiny model so the harness itself stays testable, and
labels the metric accordingly.

TPU runs are a LADDER (VERDICT r04 next-step #1): tiny 64^2 row first
(seconds of compile — banks a real `backend:"tpu"` datum immediately),
then SD2.1-768, then the flagship SDXL row, then SDXL+ControlNet. Every
row runs in its OWN subprocess with a hard timeout, and the accumulated
rows are flushed to BENCH_LADDER.json after each one — a relay wedge
mid-ladder (the exact round-3/4 failure mode) loses only the rows not yet
run, never the ones already banked. The parent process never initialises
the TPU backend itself: the axon relay is single-tenant, so exactly one
process at a time may hold a claim.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

`vs_baseline` compares against the ROOFLINE-HONEST target (see BASELINE.md
round-3 re-derivation): SDXL 1024^2 30-step CFG needs ~419 UNet TFLOP per
image, so one 197-TFLOP/s v5e chip is compute-bound at ~0.47 img/s at 100%
MFU — the target is 0.33 img/s/chip (~70% MFU), not the physically
unreachable 1.0 the round-1 BASELINE guessed.
"""

from __future__ import annotations

import json
import os
import sys
import time

# the per-chip peak table and its BENCH_PEAK_TFLOPS override live in the
# serving-path cost plane (ISSUE 17) so /metrics MFU and bench MFU share
# one denominator; jax-free, safe to import in the non-TPU parent
from chiaswarm_tpu.costs import peak_tflops

TARGET_IMG_PER_SEC_PER_CHIP = 0.33  # ~70% UNet MFU on one v5e chip


def vs_baseline(per_chip_rate: float, *, comparable: bool) -> float | None:
    """Ratio against the roofline target — ONLY for rows measuring the
    target geometry (SDXL 1024^2 30-step txt2img on TPU). Every other
    row reports null: a 64^2 4-step toy "beating" the SDXL target by
    400x was an apples-to-asteroids ratio dressed up as signal, and
    downstream dashboards treated it as one."""
    if not comparable:
        return None
    return round(per_chip_rate / TARGET_IMG_PER_SEC_PER_CHIP, 4)


def probe_tpu(timeout_s: float) -> str:
    """Check in a subprocess whether the TPU backend initialises at all.

    Returns "tpu" (TPU device present), "no-tpu" (clean init, CPU-only
    machine — don't bother retrying), or "error"/"hang" (init crashed or
    hung — worth at most a bounded retry).

    Round-1 failure modes: the TPU/axon plugin either raised UNAVAILABLE at
    `jax.default_backend()` (bench died rc=1) or hung indefinitely during
    init (multichip dryrun died rc=124).  A subprocess probe with a hard
    timeout guards against both without wedging the parent.
    """
    import subprocess

    code = (
        "import jax; ds = jax.devices(); "
        "print('PLATFORMS', sorted({d.platform for d in ds}))"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        sys.stderr.write(f"tpu probe timed out after {timeout_s:.0f}s\n")
        return "hang"
    platforms = []
    for line in out.stdout.splitlines():
        if line.startswith("PLATFORMS "):
            platforms = [p.strip("[]', ") for p in line[10:].split(",")]
    if out.returncode != 0 or not platforms:
        sys.stderr.write(
            f"tpu probe rc={out.returncode} out={out.stdout!r} "
            f"err tail={out.stderr[-300:]!r}\n"
        )
        return "error"
    return "tpu" if "tpu" in platforms else "no-tpu"


def probe_loop() -> bool:
    """Bounded probe ladder deciding TPU vs CPU-fallback. Never imports
    jax in this process — the single-tenant relay must stay free for the
    row subprocesses."""
    probe_budget = float(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "300"))
    attempts = int(os.environ.get("BENCH_TPU_PROBE_ATTEMPTS", "3"))
    hangs = 0
    for attempt in range(attempts):
        status = probe_tpu(probe_budget)
        if status == "tpu":
            return True
        if status == "no-tpu":
            return False
        if status == "hang":
            # a HANGING relay (observed wedged for 8+ hours in round 4)
            # is not cured by retrying — two consecutive full-budget
            # hangs and we take the labelled CPU fallback instead of
            # starving the driver's bench budget (round-3 failure mode)
            hangs += 1
            if hangs >= 2:
                sys.stderr.write(
                    "tpu relay hangs persistently; giving up early\n"
                )
                return False
        else:
            hangs = 0
        if attempt + 1 < attempts:
            # relay/plugin restarts have been observed to take minutes;
            # back off harder each retry (VERDICT r03 weak #1)
            time.sleep(30 * (attempt + 1))
    return False


def _enable_compile_cache(min_compile_time_s: float = 1.0) -> None:
    """Same persistent XLA cache the worker uses (compile_cache.py) — the
    bench both exercises it (warm-restart probe) and leaves it populated."""
    try:
        from chiaswarm_tpu.compile_cache import enable_compile_cache
        from chiaswarm_tpu.settings import load_settings

        enable_compile_cache(load_settings(),
                             min_compile_time_s=min_compile_time_s)
    except Exception as e:
        sys.stderr.write(f"compilation cache unavailable: {e}\n")


# ---------------------------------------------------------------------------
# TPU ladder (parent side)

# (row name, default subprocess timeout seconds). The SDXL cold compile
# measured 369 s in round 2; budgets leave ~4x headroom on top of the
# 3x timed runs. Override per row via BENCH_ROW_TIMEOUT_<NAME>.
_LADDER_ROWS = [
    ("tiny", 900.0),
    ("batched", 900.0),
    ("sd21", 1800.0),
    ("sdxl", 2700.0),
    ("controlnet", 1500.0),
]


def _row_timeout(name: str, default: float) -> float:
    return float(os.environ.get(f"BENCH_ROW_TIMEOUT_{name.upper()}", default))


def _parse_last_json(text: str):
    for line in reversed(text.splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                return None
    return None


def _run_row_attempt(name: str, timeout_s: float,
                     disable_kernels: bool) -> tuple[dict, bool]:
    """One row-child invocation -> (row_json_or_error, timed_out)."""
    import subprocess

    env = None
    if disable_kernels:
        env = dict(os.environ, CHIASWARM_DISABLE_FUSED_GN="1",
                   CHIASWARM_DISABLE_FLASH="1")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--row", name],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-4000:] + "\n")
        row = _parse_last_json(proc.stdout)
        if row is None:
            row = {
                "error": f"row produced no JSON (rc={proc.returncode})",
                "stderr_tail": proc.stderr[-500:],
            }
        timed_out = False
    except subprocess.TimeoutExpired as e:
        sys.stderr.write(f"[ladder] row {name} TIMED OUT\n")
        if e.stderr:
            tail = e.stderr if isinstance(e.stderr, str) else \
                e.stderr.decode("utf-8", "replace")
            sys.stderr.write(tail[-2000:] + "\n")
        # the child prints its metric row BEFORE best-effort extras
        # (warm-compile probe), so a timeout there must not discard a
        # measured number: recover it from the partial stdout
        partial = e.stdout if isinstance(e.stdout, str) else (
            e.stdout.decode("utf-8", "replace") if e.stdout else "")
        row = _parse_last_json(partial)
        if row is not None and row.get("value"):
            row["row_timed_out"] = f"after {timeout_s:.0f}s (row banked)"
        else:
            row = {"error": f"timeout after {timeout_s:.0f}s"}
        timed_out = True
    if disable_kernels:
        # the label must survive BOTH exit paths — a kernels-disabled
        # measurement published as a fused-kernel number would corrupt
        # the A/B record
        row["kernels_disabled_fallback"] = True
    return row, timed_out


def _kernel_retry_pointless(row: dict) -> bool:
    """Disabling Pallas kernels cannot cure relay/backend failures or
    timeouts — retrying those only burns the single-tenant TPU window."""
    err = str(row.get("error", ""))
    return any(s in err for s in ("no TPU device", "backend init", "timeout"))


def run_ladder() -> dict:
    """Run each TPU row in its own subprocess; accumulate and persist.

    Returns the merged ladder dict {row_name: row_json_or_error}."""
    ladder_path = os.environ.get("BENCH_LADDER_FILE", "BENCH_LADDER.json")
    full = os.environ.get("BENCH_CONFIGS", "full") == "full"
    rows = [r for r in _LADDER_ROWS if full or r[0] != "controlnet"]
    ladder: dict = {}
    for name, default_timeout in rows:
        timeout_s = _row_timeout(name, default_timeout)
        sys.stderr.write(f"[ladder] row {name} (timeout {timeout_s:.0f}s)\n")
        t0 = time.perf_counter()
        row, timed_out = _run_row_attempt(name, timeout_s, False)
        if not row.get("value") and name != "tiny" \
                and not _kernel_retry_pointless(row):
            # an errored row may be a Pallas kernel the hermetic suite
            # couldn't compile-check on real hardware: one retry with the
            # custom kernels disabled trades speed for banking the row
            sys.stderr.write(
                f"[ladder] row {name} errored; retrying with "
                "CHIASWARM_DISABLE_FUSED_GN=1 CHIASWARM_DISABLE_FLASH=1\n")
            retry, timed_out = _run_row_attempt(name, timeout_s, True)
            if retry.get("value"):
                retry["first_attempt_error"] = str(row.get("error", "?"))
                row = retry
            elif retry.get("error"):
                row.setdefault("retry_error", str(retry["error"]))
        row["row_wall_s"] = round(time.perf_counter() - t0, 1)
        ladder[name] = row
        _flush_ladder(ladder_path, ladder)
        if timed_out:
            # a timed-out row often wedges the relay under the killed
            # claim — but relay/plugin restarts are also documented to
            # take minutes, so give recovery a few probes before
            # abandoning the rows that remain
            recovered = False
            for _ in range(3):
                if probe_tpu(120.0) == "tpu":
                    recovered = True
                    break
                time.sleep(60)
            if not recovered:
                ladder["relay_wedged_after"] = name
                _flush_ladder(ladder_path, ladder)
                break
    return ladder


def _flush_ladder(path: str, ladder: dict) -> None:
    try:
        with open(path, "w") as f:
            json.dump(ladder, f, indent=1)
    except OSError as e:
        sys.stderr.write(f"ladder flush failed: {e}\n")


def _compose_from_ladder(ladder: dict) -> dict | None:
    """Pick the best banked row as the primary metric; merge the rest.

    Preference: sdxl (flagship) > sd21 > tiny. Secondary keys keep their
    TPU-shaped names only when they are real TPU rows."""
    out: dict = {}
    sd21 = ladder.get("sd21") or {}
    tiny = ladder.get("tiny") or {}
    cnet = ladder.get("controlnet") or {}
    sdxl = ladder.get("sdxl") or {}

    if sdxl.get("value"):
        out.update(sdxl)
    elif sd21.get("value"):
        out.update(sd21)
        out["primary_row_failed"] = str(ladder.get("sdxl", {}).get(
            "error", "sdxl row absent"))
    elif tiny.get("value"):
        out.update(tiny)
        out["primary_row_failed"] = str(ladder.get("sdxl", {}).get(
            "error", "sdxl row absent"))
    else:
        return None

    if sd21.get("value") and out.get("metric") != sd21.get("metric"):
        out["sd21_768_img_per_sec_per_chip"] = sd21["value"]
        out["sd21_768_p50_job_s"] = sd21.get("p50_job_s")
        if sd21.get("unet_mfu") is not None:
            out["sd21_768_unet_mfu"] = sd21["unet_mfu"]
    elif sd21.get("error") and out.get("metric") != sd21.get("metric"):
        out["sd21_768_row"] = f"failed: {sd21['error']}"

    if tiny.get("value") and out.get("metric") != tiny.get("metric"):
        out["tiny_tpu_img_per_sec_per_chip"] = tiny["value"]
        out["tiny_tpu_p50_job_s"] = tiny.get("p50_job_s")

    if cnet:
        if cnet.get("value"):
            out["sdxl_controlnet_img_per_sec_per_chip"] = cnet["value"]
            out["sdxl_controlnet_p50_job_s"] = cnet.get("p50_job_s")
        else:
            out["sdxl_controlnet_row"] = f"failed: {cnet.get('error')}"

    batched = ladder.get("batched") or {}
    # merge whatever sub-rows landed — an x4 failure must not discard the
    # banked x1/x2 rates or the per-factor failure diagnostics
    out.update({
        k: v for k, v in batched.items() if k.startswith("batched_")
    })
    if not batched.get("value") and batched.get("error"):
        out["batched_txt2img_row"] = f"failed: {batched['error']}"
    if "relay_wedged_after" in ladder:
        out["relay_wedged_after"] = ladder["relay_wedged_after"]
    return out


# ---------------------------------------------------------------------------
# Row children (each runs in its own process, sole tenant of the relay)

def run_row(name: str) -> None:
    """Execute one bench row against the ambient (TPU) backend and print
    its JSON. Exit nonzero without output only on backend-init failure."""
    _enable_compile_cache()
    import jax

    try:
        chips = jax.devices()
    except Exception as e:
        print(json.dumps({"error": f"backend init: {type(e).__name__}: {e}"}))
        raise SystemExit(1)
    if not any(d.platform == "tpu" for d in chips):
        print(json.dumps({"error": "no TPU device in row child"}))
        raise SystemExit(1)

    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    chipset = ChipSet(chips)
    n = len(chips)

    if name == "tiny":
        pipe = SDPipeline("test/tiny-sd", chipset=chipset,
                          allow_random_init=True)
        rate, p50, batch, extra = run_config(pipe, 64, 4, 4)
        out = {
            "metric": "tiny_txt2img_tpu_smoke_images_per_sec_per_chip",
            "value": round(rate / n, 4),
            "unit": "images/sec/chip",
            "vs_baseline": vs_baseline(rate / n, comparable=False),
            "p50_job_s": round(p50, 3), "batch": batch, "chips": n,
            "backend": "tpu", "steps": 4, "size": 64, **extra,
        }
    elif name == "batched":
        # cross-job micro-batching (chiaswarm_tpu/batching.py): one padded
        # denoise+decode pass for 1/2/4 coalesced single-image jobs; the
        # win is the amortized per-pass overhead + fuller MXU
        pipe = SDPipeline("test/tiny-sd", chipset=chipset,
                          allow_random_init=True)
        rows = _batched_rows(pipe, n)
        out = {
            "metric": "batched_txt2img_tiny_tpu_x4_images_per_sec_per_chip",
            "value": rows.get("batched_txt2img_x4_img_per_sec_per_chip", 0.0),
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,  # throughput ladder row, no roofline target
            "chips": n, "backend": "tpu", "steps": 4, "size": 64,
            **rows,
        }
    elif name == "sd21":
        pipe = SDPipeline("stabilityai/stable-diffusion-2-1",
                          chipset=chipset, allow_random_init=True)
        rate, p50, batch, extra = run_config(pipe, 768, 30, 4)
        out = {
            "metric": "sd21_txt2img_768_30step_images_per_sec_per_chip",
            "value": round(rate / n, 4),
            "unit": "images/sec/chip",
            "vs_baseline": vs_baseline(rate / n, comparable=False),
            "p50_job_s": round(p50, 3), "batch": batch, "chips": n,
            "backend": "tpu", "steps": 30, "size": 768, **extra,
        }
    elif name == "sdxl":
        pipe = SDPipeline("stabilityai/stable-diffusion-xl-base-1.0",
                          chipset=chipset, allow_random_init=True)
        batch_candidates = [int(os.environ.get("BENCH_BATCH", 0)) or 4, 2, 1]
        result = None
        for batch in batch_candidates:
            try:
                result = run_config(pipe, 1024, 30, batch)
                break
            except Exception as e:  # OOM on small chips -> smaller batch
                sys.stderr.write(
                    f"batch={batch} failed: {type(e).__name__}: {e}\n")
        if result is None:
            print(json.dumps({"error": "all batch sizes failed"}))
            raise SystemExit(1)
        rate, p50, batch, extra = result
        out = {
            "metric": "sdxl_txt2img_1024_30step_images_per_sec_per_chip",
            "value": round(rate / n, 4),
            "unit": "images/sec/chip",
            # the ONE row measuring the target geometry
            "vs_baseline": vs_baseline(rate / n, comparable=True),
            "target_img_per_sec_per_chip": TARGET_IMG_PER_SEC_PER_CHIP,
            "p50_job_s": round(p50, 3), "batch": batch, "chips": n,
            "backend": "tpu", "steps": 30, "size": 1024, **extra,
        }
        # bank the measured metric BEFORE the best-effort warm probe: the
        # parent recovers the last JSON line from partial stdout if this
        # child is killed mid-probe
        print(json.dumps(out), flush=True)
        out.update(_warm_compile_probe(pipe, 1024, 30, batch))
    elif name == "flux":
        # streamed Flux-schnell on whatever slice this is: on one 16 GB
        # chip the 12B transformer pages from host RAM (weight streaming),
        # measuring the small-worker serving mode the reference covers
        # with sequential CPU offload. Sweep-only row (not in the ladder).
        from chiaswarm_tpu.pipelines.flux import FluxPipeline

        pipe = FluxPipeline("black-forest-labs/FLUX.1-schnell",
                            chipset=chipset, allow_random_init=True)
        times = []
        kwf = dict(prompt="bench", height=1024, width=1024,
                   num_inference_steps=4, guidance_scale=0)
        pipe.run(rng=jax.random.key(0), **kwf)  # compile + first page-through
        for i in range(3):
            t0 = time.perf_counter()
            pipe.run(rng=jax.random.key(i + 1), **kwf)
            times.append(time.perf_counter() - t0)
        p50 = sorted(times)[1]
        out = {
            "metric": "flux_schnell_1024_4step_images_per_sec_per_chip",
            "value": round(1.0 / p50 / n, 4),
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,  # no reference/baseline row for flux
            "p50_job_s": round(p50, 3), "chips": n, "backend": "tpu",
            "steps": 4, "size": 1024,
            "weight_streaming": pipe.streaming,
        }
    elif name == "controlnet":
        from PIL import Image

        pipe = SDPipeline("stabilityai/stable-diffusion-xl-base-1.0",
                          chipset=chipset, allow_random_init=True)
        rate, p50 = _quick_rate(
            pipe,
            dict(height=1024, width=1024, num_inference_steps=30,
                 num_images_per_prompt=2,
                 controlnet_model_name="diffusers/controlnet-canny-sdxl-1.0",
                 image=Image.new("RGB", (1024, 1024), (128, 128, 128)),
                 scheduler_type="EulerDiscreteScheduler"),
        )
        out = {
            "metric": "sdxl_controlnet_1024_30step_images_per_sec_per_chip",
            "value": round(rate / n, 4),
            "unit": "images/sec/chip",
            # target geometry but extra (ControlNet) work — not the
            # roofline the target was derived for
            "vs_baseline": vs_baseline(rate / n, comparable=False),
            "p50_job_s": round(p50, 3), "chips": n, "backend": "tpu",
            "steps": 30, "size": 1024,
        }
    else:
        raise SystemExit(f"unknown row {name!r}")
    print(json.dumps(out))


# ---------------------------------------------------------------------------
# CPU fallback (in-process; exercised hermetically by tests/test_bench.py)

def cpu_smoke(extra_fields: dict | None = None,
              tpu_present: bool = False) -> None:
    import jax

    sys.stderr.write("TPU unavailable -> CPU fallback bench\n")
    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    try:
        chips = jax.devices()
    except Exception as e:
        print(json.dumps({
            "metric": "bench_backend_init_failed",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }))
        raise SystemExit(0)

    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    chipset = ChipSet(chips)
    # the smoke row only proves the harness; 4 steps keep the CPU
    # fallback (and its CI contract test) fast
    size, steps, batch = 64, 4, 4

    # perf does not depend on weight values: converted weights load from the
    # model root when present, else the bench opts into random init (the
    # worker's serving path never does — weights.py policy)
    pipe = SDPipeline("test/tiny-sd", chipset=chipset, allow_random_init=True)
    images_per_sec, p50_job_s, batch, extra = run_config(
        pipe, size, steps, batch)
    per_chip = images_per_sec / len(chips)
    out = {
        "metric": "tiny_txt2img_cpu_smoke_images_per_sec_per_chip",
        "value": round(per_chip, 4),
        "unit": "images/sec/chip",
        # a 64^2 4-step CPU toy vs the SDXL TPU roofline target is not a
        # comparison — null, pinned by test_bench
        "vs_baseline": vs_baseline(per_chip, comparable=False),
        "target_img_per_sec_per_chip": TARGET_IMG_PER_SEC_PER_CHIP,
        "p50_job_s": round(p50_job_s, 3),
        "batch": batch,
        "chips": len(chips),
        "backend": jax.default_backend(),
        "steps": steps,
        "size": size,
        # never let a CPU smoke number pass silently for a TPU datum
        # (VERDICT r03: the artifact itself must say why the TPU datum is
        # absent — tpu_unavailable when no chip answered, tpu_ladder_failed
        # when the chip answered but every row died)
        "tpu_unavailable": not tpu_present,
        **extra,
    }
    if extra_fields:
        out.update(extra_fields)

    # cross-job micro-batching row (batching.py), same tiny smoke config:
    # images/sec/chip at coalesce factors 1/2/4 so the scheduler's win is
    # a number in BENCH_*.json, not a claim. Runs in its own subprocess on
    # a 4-virtual-device slice: the win being measured is slice FILL — a
    # batch-1 job's CFG pair can't shard a 4-chip data axis (it
    # replicates), a coalesced batch can — and this process is pinned to
    # one device for the primary metric's continuity.
    out.update(_batched_cpu_row_subprocess())

    # priority-aware multi-chip sharding row (ISSUE 12): one job, many
    # chips — tensor=1/2/4 mesh views over an 8-virtual-device slice,
    # with the sharded-vs-replicated max-abs diff as the numerics bar
    out.update(_sharded_cpu_row_subprocess())

    # multi-tenant adapter serving row (ISSUE 13): 4 distinct LoRAs on
    # one base model as ONE mixed-adapter coalesced pass (runtime
    # per-row deltas) vs the solo-merged baseline, plus the
    # delta-vs-merged numerics bar and the dispatcher gang smoke
    out.update(_lora_coalesce_row_subprocess())

    # persistent-compile-cache restart probe: two fresh processes sharing
    # one cache dir — the second's cold-start must be well under the
    # first's (the tentpole claim that warmup survives restarts)
    out.update(_warm_restart_rows())

    # residency-aware placement smoke: affinity_hit_rate / steals from
    # the real dispatch-board claim path on a 2-slice virtual allocator
    out.update(_placement_row_subprocess())

    # whole-swarm-loop row (ISSUE 5): hive_server + a pristine worker
    # subprocess over real sockets — jobs/s, hive queue-wait, redeliveries
    out.update(_hive_e2e_row_subprocess())

    # hive durability row (ISSUE 6): enqueue N jobs, SIGKILL the hive,
    # restart over the same $SDAAS_ROOT — recovery time and jobs lost
    # (must be 0; the WAL replay is the claim under test)
    out.update(_hive_restart_row_subprocess())

    # hive availability row (ISSUE 7): primary + WAL-shipped standby +
    # echo worker; primary killed mid-run, standby health-checks it dead
    # and promotes — takeover time and jobs lost (must be 0)
    out.update(_hive_failover_row_subprocess())

    # BENCH_FORCE_SECONDARY exercises the warm-probe + secondary-row code
    # paths on CPU with tiny models (they had never executed before a TPU
    # run — VERDICT r03 weak #4)
    if os.environ.get("BENCH_FORCE_SECONDARY", "") not in ("", "0"):
        out.update(_warm_compile_probe(pipe, size, steps, batch))
        out.update(_secondary_rows(chipset, chips, pipe))

    print(json.dumps(out))


def main() -> None:
    if probe_loop():
        ladder = run_ladder()
        out = _compose_from_ladder(ladder)
        if out is not None:
            print(json.dumps(out))
            return
        # chip answered the probe but every row died: fall through to the
        # labelled CPU smoke so the driver still gets a number, with the
        # ladder failure visible in the artifact
        cpu_smoke({"tpu_ladder_failed": {
            k: str(v.get("error", "?")) if isinstance(v, dict) else str(v)
            for k, v in ladder.items()}}, tpu_present=True)
    else:
        cpu_smoke()


def _warm_compile_probe(pipe, size, steps, batch) -> dict:
    """Prove the persistent compile cache: drop every in-memory executable,
    re-trace the SAME shape bucket, and time the rebuild — a worker restart
    pays this, not the cold compile (VERDICT weak #2)."""
    import jax

    try:
        jax.clear_caches()
        pipe._programs.clear()
        t0 = time.perf_counter()
        pipe.run(
            prompt="warm probe",
            height=size,
            width=size,
            num_inference_steps=steps,
            num_images_per_prompt=batch,
            scheduler_type="EulerDiscreteScheduler",
            rng=jax.random.key(99),
        )
        return {"warm_compile_s": round(time.perf_counter() - t0, 1)}
    except Exception as e:
        sys.stderr.write(f"warm-compile probe failed: {e}\n")
        # failure must be visible in the artifact, not just stderr
        return {"warm_compile_s": f"failed: {type(e).__name__}: {e}"}


def _secondary_rows(chipset, chips, xl_pipe) -> dict:
    """Tiny-model ControlNet + second-family smoke rows for the hermetic
    CPU path (the TPU ladder runs the real equivalents as their own
    subprocess rows in run_row instead)."""
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    size, steps = 64, 2
    out = {}
    try:
        from PIL import Image

        rate, p50 = _quick_rate(
            xl_pipe,
            dict(height=size, width=size, num_inference_steps=steps,
                 num_images_per_prompt=2,
                 controlnet_model_name="test/tiny-controlnet",
                 image=Image.new("RGB", (size, size), (128, 128, 128)),
                 scheduler_type="EulerDiscreteScheduler"),
        )
        out["tiny_controlnet_smoke_img_per_sec_per_chip"] = round(
            rate / len(chips), 4)
        out["tiny_controlnet_smoke_p50_job_s"] = round(p50, 3)
    except Exception as e:
        sys.stderr.write(f"controlnet row failed: {type(e).__name__}: {e}\n")
        out["tiny_controlnet_smoke_row"] = f"failed: {type(e).__name__}: {e}"
    try:
        xl_pipe.release()  # free memory before the second pipeline
        sd = SDPipeline("test/tiny-sd", chipset=chipset,
                        allow_random_init=True)
        rate, p50 = _quick_rate(
            sd, dict(height=size, width=size, num_inference_steps=steps,
                     num_images_per_prompt=4,
                     scheduler_type="EulerDiscreteScheduler")
        )
        out["tiny_sd_smoke_img_per_sec_per_chip"] = round(
            rate / len(chips), 4)
        out["tiny_sd_smoke_p50_job_s"] = round(p50, 3)
        sd.release()
    except Exception as e:
        sys.stderr.write(f"sd21 row failed: {type(e).__name__}: {e}\n")
        out["tiny_sd_smoke_row"] = f"failed: {type(e).__name__}: {e}"
    return out


def _batched_cpu_row_subprocess() -> dict:
    """Spawn the CPU batched row on a 4-virtual-device slice (the same
    virtual-chip trick the hermetic test mesh uses): device count is
    frozen at first jax import, so a fresh process is the only way to
    model a multi-chip slice next to the 1-device primary smoke row."""
    import subprocess

    timeout_s = _row_timeout("batched_cpu", 900.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--row", "batched-cpu"],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        row = _parse_last_json(proc.stdout)
        if row is None:
            row = {"batched_txt2img_row":
                   f"failed: no JSON (rc={proc.returncode})"}
    except subprocess.TimeoutExpired:
        row = {"batched_txt2img_row": f"failed: timeout after {timeout_s:.0f}s"}
    return row


def _lora_coalesce_row_subprocess() -> dict:
    """Spawn the multi-tenant adapter row (ISSUE 13) on a 4-virtual-
    device slice: 4 jobs with 4 DISTINCT LoRA adapters on one tiny base
    model, served as ONE mixed-adapter coalesced pass (runtime per-row
    deltas) vs the solo-merged baseline (one pass + one merged param
    tree per adapter — the pre-ISSUE-13 serving shape)."""
    import subprocess

    timeout_s = _row_timeout("lora_coalesce", 900.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    # the row toggles the delta knob itself; a parent override would
    # make the two legs measure the same path
    env.pop("CHIASWARM_LORA_RUNTIME_DELTA", None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--row", "lora-coalesce-cpu"],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        row = _parse_last_json(proc.stdout)
        if row is None:
            row = {"lora_coalesce_row":
                   f"failed: no JSON (rc={proc.returncode})"}
    except subprocess.TimeoutExpired:
        row = {"lora_coalesce_row": f"failed: timeout after {timeout_s:.0f}s"}
    return row


def run_lora_coalesce_row() -> None:
    """Child for the lora_coalesce row (ISSUE 13): mixed-adapter
    coalesced serving vs solo-merged, plus the delta-vs-merged numerics
    bar, the adapter factor-cache hit rate, and a jax-free gang smoke
    proving the hive dispatcher gangs adapter jobs."""
    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    import numpy as np
    from safetensors.numpy import save_file

    from chiaswarm_tpu import lora_cache
    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    chips = jax.devices()
    pipe = SDPipeline("test/tiny-sd", chipset=ChipSet(chips),
                      allow_random_init=True)
    cache = lora_cache.configure(256 * 1024 * 1024)

    # 4 distinct rank-4 adapters over the tiny UNet's attention kernels
    unet = pipe.params["unet"]
    q_dim = int(unet["down_blocks_0"]["attentions_0"]["transformer_blocks_0"]
                ["attn1"]["to_q"]["kernel"].shape[0])
    adapter_dir = tempfile.mkdtemp(prefix="bench_lora_")
    base_key = "unet.down_blocks.0.attentions.0.transformer_blocks.0"
    refs = []
    for i in range(4):
        rng = np.random.default_rng(1000 + i)
        state = {}
        for proj in ("attn1.to_q", "attn2.to_v"):
            state[f"{base_key}.{proj}.lora_A.weight"] = \
                0.05 * rng.standard_normal((4, q_dim)).astype(np.float32)
            state[f"{base_key}.{proj}.lora_B.weight"] = \
                0.05 * rng.standard_normal((q_dim, 4)).astype(np.float32)
        path = os.path.join(adapter_dir, f"adapter_{i}.safetensors")
        save_file(state, path)
        refs.append({"lora": path})

    # steps=2 and 2 timed reps: compiles dominate this row's wall clock
    # (3 distinct programs), and the ratio under test is per-PASS — the
    # tier-1 budget shares one 870 s window with the whole bench
    size, steps = 64, 2
    shared = dict(height=size, width=size, num_inference_steps=steps,
                  guidance_scale=7.5,
                  scheduler_type="EulerDiscreteScheduler")
    out: dict = {}

    # --- leg 1: ONE mixed-adapter coalesced pass (runtime deltas) ---
    # pin the kill switch ON via env (wins over a host settings.json
    # carrying lora_runtime_delta=false): the row toggles the knob per
    # leg and must not inherit fleet config
    os.environ["CHIASWARM_LORA_RUNTIME_DELTA"] = "1"
    requests = [
        dict(prompt=f"tenant {i}", negative_prompt="",
             num_images_per_prompt=1, rng=jax.random.key(500 + i),
             lora=refs[i], lora_scale=1.0)
        for i in range(4)
    ]
    pipe.run_batched(requests, **shared)  # compile + factor loads
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        ganged = pipe.run_batched(requests, **shared)
        times.append(time.perf_counter() - t0)
    ganged_p50 = min(times)
    ganged_rate = 4 / ganged_p50 / len(chips)
    assert all(cfg.get("lora_mode") == "delta" for _, cfg in ganged)

    # --- steady-state operand residency (ISSUE 16): the SAME repeat
    # gang with the operand cache dropped before each pass (cold:
    # re-assemble + re-upload every A/B stack) vs left resident
    # (steady: dict lookup hands jit the device-resident operands,
    # zero upload). The compiled program is identical either way —
    # the delta is pure operand assembly + transfer. ---
    from chiaswarm_tpu import lora_operands
    from chiaswarm_tpu.lora_operands import _EVENTS as _OPERAND_EVENTS

    cold_times = []
    for _ in range(2):
        # configure() frees every resident entry: next pass is cold
        lora_operands.configure(256 * 1024 * 1024)
        t0 = time.perf_counter()
        pipe.run_batched(requests, **shared)
        cold_times.append(time.perf_counter() - t0)
    cold_p50 = min(cold_times)
    # the last cold pass left the stacks resident; these reps hit
    op_hits0 = _OPERAND_EVENTS.value(event="hit")
    op_miss0 = _OPERAND_EVENTS.value(event="miss")
    steady_times, upload_saved = [], 0
    for _ in range(2):
        t0 = time.perf_counter()
        pipe.run_batched(requests, **shared)
        steady_times.append(time.perf_counter() - t0)
        stats = pipe.last_operand_stats or {}
        upload_saved += int(stats.get("bytes_saved", 0))
    steady_p50 = min(steady_times)
    op_hits = _OPERAND_EVENTS.value(event="hit") - op_hits0
    op_miss = _OPERAND_EVENTS.value(event="miss") - op_miss0
    operand_hit_rate = (op_hits / (op_hits + op_miss)
                        if op_hits + op_miss else 0.0)

    # --- leg 2: solo-merged baseline, both regimes of the old serving
    # shape. THRASHING: 4 adapters > the merged LRU (2), every cycle
    # re-merges + re-places a full UNet copy — the fleet-realistic
    # multi-tenant regime (a real census of adapters dwarfs any
    # whole-tree LRU; 4-over-2 reproduces the thrash in miniature) and
    # the headline this ISSUE's speedup is quoted against. RESIDENT:
    # the LRU raised to the pre-ISSUE-13 cap of 4 so all merged trees
    # stay warm — the literal 4-adapter best case of the old code,
    # isolating the pure coalescing win (1 padded pass vs 4 passes)
    # from the re-merge cost. Reporting both keeps the headline honest.
    from chiaswarm_tpu.pipelines import stable_diffusion as sd_mod

    os.environ["CHIASWARM_LORA_RUNTIME_DELTA"] = "0"
    try:
        solo_kw = [dict(prompt=f"tenant {i}", rng=jax.random.key(500 + i),
                        lora=refs[i], lora_scale=1.0, **shared)
                   for i in range(4)]
        for kw in solo_kw:
            pipe.run(**dict(kw))  # compile + first merges
        times = []
        for _ in range(2):
            t0 = time.perf_counter()
            for kw in solo_kw:
                pipe.run(**dict(kw))
            times.append(time.perf_counter() - t0)
        solo_p50 = min(times)
        solo_rate = 4 / solo_p50 / len(chips)

        old_cap = sd_mod.MAX_RESIDENT_LORAS
        sd_mod.MAX_RESIDENT_LORAS = 4
        try:
            for kw in solo_kw:
                pipe.run(**dict(kw))  # warm all 4 merged trees resident
            times = []
            for _ in range(2):
                t0 = time.perf_counter()
                for kw in solo_kw:
                    pipe.run(**dict(kw))
                times.append(time.perf_counter() - t0)
            resident_p50 = min(times)
            resident_rate = 4 / resident_p50 / len(chips)
        finally:
            sd_mod.MAX_RESIDENT_LORAS = old_cap
            pipe._lora_cache.clear()

        # --- numerics bar: the SAME solo job served by the delta path vs
        # the merged tree (identical rng/noise path) must agree to the
        # uint8 rounding boundary ---
        merged_img = np.asarray(pipe.run(**dict(solo_kw[0]))[0][0],
                                np.int32)
    finally:
        # back to "1" (not a pop): the delta-path probe below must not
        # inherit a host settings.json kill switch either
        os.environ["CHIASWARM_LORA_RUNTIME_DELTA"] = "1"
    delta_img = np.asarray(pipe.run(**dict(solo_kw[0]))[0][0], np.int32)
    maxdiff = int(np.abs(delta_img - merged_img).max())

    # --- adapter factor-cache effectiveness across both legs ---
    from chiaswarm_tpu.lora_cache import _EVENTS as _LORA_CACHE_EVENTS

    hits = _LORA_CACHE_EVENTS.value(event="hit")
    misses = _LORA_CACHE_EVENTS.value(event="miss")

    # --- jax-free hive gang smoke: 4 adapter jobs, one poll, one gang ---
    from chiaswarm_tpu.hive_server.dispatch import Dispatcher, WorkerDirectory
    from chiaswarm_tpu.hive_server.queue import PriorityJobQueue

    directory = WorkerDirectory(ttl_s=45.0)
    dispatcher = Dispatcher(directory, affinity_hold_s=0.0,
                            max_jobs_per_poll=8, gang_max=8, lora_slots=8)
    queue = PriorityJobQueue()
    for i in range(4):
        queue.submit({
            "id": f"bench-lora-{i}", "workflow": "txt2img",
            "model_name": "stabilityai/stable-diffusion-2-1",
            "lora": f"tenant-style-{i}", "prompt": "x",
            "height": 64, "width": 64, "num_inference_steps": steps,
            "parameters": {"test_tiny_model": True},
        })
    worker = directory.observe({
        "worker_name": "bench", "worker_version": "0.1.0", "slices": "1",
        "busy_slices": "0", "queue_depth": "0", "gang_rows": "8"})
    handed = dispatcher.select(worker, queue)
    gang_members = sum(1 for _, _, g in handed if g is not None)

    out.update({
        "lora_coalesce_ganged_img_per_sec_per_chip": round(ganged_rate, 4),
        "lora_coalesce_ganged_p50_pass_s": round(ganged_p50, 3),
        "lora_coalesce_solo_merged_img_per_sec_per_chip":
            round(solo_rate, 4),
        "lora_coalesce_solo_merged_p50_cycle_s": round(solo_p50, 3),
        "lora_coalesce_solo_resident_img_per_sec_per_chip":
            round(resident_rate, 4),
        "lora_coalesce_solo_resident_p50_cycle_s": round(resident_p50, 3),
        "lora_coalesce_speedup": round(ganged_rate / solo_rate, 3)
        if solo_rate else 0.0,
        "lora_coalesce_speedup_vs_resident":
            round(ganged_rate / resident_rate, 3) if resident_rate else 0.0,
        "lora_coalesce_cold_pass_s": round(cold_p50, 3),
        "lora_coalesce_steady_p50_pass_s": round(steady_p50, 3),
        "lora_coalesce_operand_hit_rate": round(operand_hit_rate, 4),
        "lora_coalesce_upload_bytes_saved": upload_saved,
        "lora_delta_vs_merged_maxdiff": maxdiff,
        "lora_cache_hit_rate": round(hits / (hits + misses), 4)
        if hits + misses else 0.0,
        "lora_cache_resident_entries": len(cache) if cache else 0,
        "lora_gang_rate": round(gang_members / 4, 4),
        "lora_adapters": 4,
        "lora_slice_devices": len(chips),
    })
    print(json.dumps(out))


def _sharded_cpu_row_subprocess() -> dict:
    """Spawn the sharded-geometry row on an 8-virtual-device slice (the
    MULTICHIP test mesh): one interactive-shaped txt2img pass at
    tensor=1/2/4 over the SAME chips, reporting per-geometry latency and
    the sharded-vs-replicated max-abs pixel diff (the numerics-clean
    acceptance bar). A fresh process because device count freezes at
    first jax import."""
    import subprocess

    timeout_s = _row_timeout("sharded_cpu", 900.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--row", "sharded-cpu"],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        row = _parse_last_json(proc.stdout)
        if row is None:
            row = {"sharded_txt2img_row":
                   f"failed: no JSON (rc={proc.returncode})"}
    except subprocess.TimeoutExpired:
        row = {"sharded_txt2img_row": f"failed: timeout after {timeout_s:.0f}s"}
    return row


def run_sharded_cpu_row() -> None:
    """Child for the sharded-geometry row (ISSUE 12): ONE batch-1 job on
    an 8-device slice under tensor=1 (replicated — the single-chip-bound
    baseline the ROADMAP names), tensor=2, and tensor=4 mesh views, plus
    the max-abs uint8 diff of each sharded output against the replicated
    one. On real multi-chip hardware the latency column is the tentpole
    claim (a single job faster than one chip); on the virtual CPU mesh
    the diff column is the load-bearing number and the latencies prove
    the geometry path end-to-end."""
    import jax
    import numpy as np

    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    chips = jax.devices()

    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    size, steps = 64, 4
    pipe = SDPipeline("test/tiny-sd", chipset=ChipSet(chips),
                      allow_random_init=True)
    out: dict = {"sharded_slice_devices": len(chips)}
    kw = dict(prompt="sharded bench", height=size, width=size,
              num_inference_steps=steps,
              scheduler_type="EulerDiscreteScheduler")
    reference = None
    for tensor in (1, 2, 4):
        if len(chips) % tensor:
            continue
        geometry = {"tensor": tensor}
        try:
            pipe.run(rng=jax.random.key(7), geometry=geometry, **kw)  # compile
            times = []
            last = None
            for _ in range(3):
                t0 = time.perf_counter()
                last, cfg = pipe.run(rng=jax.random.key(7),
                                     geometry=geometry, **kw)
                times.append(time.perf_counter() - t0)
            p50 = sorted(times)[1]
            out[f"sharded_txt2img_t{tensor}_p50_s"] = round(p50, 3)
            out[f"sharded_txt2img_t{tensor}_geometry"] = cfg["geometry"]
            # serving-path cost stamp (ISSUE 17): the same figures the
            # envelope carries — fleet TFLOP/s over the denoise span and
            # MFU (null on CPU, no peak-TFLOPs entry)
            cost = cfg.get("cost") or {}
            out[f"sharded_txt2img_t{tensor}_fleet_tflops"] = \
                cost.get("tflops_per_s")
            out[f"sharded_txt2img_t{tensor}_mfu"] = cost.get("mfu")
            pixels = np.asarray(last[0], np.int16)
            if tensor == 1:
                reference = pixels
            elif reference is not None:
                out[f"sharded_txt2img_t{tensor}_maxdiff"] = int(
                    np.abs(pixels - reference).max())
        except Exception as e:
            sys.stderr.write(
                f"sharded row t{tensor} failed: {type(e).__name__}: {e}\n")
            out[f"sharded_txt2img_t{tensor}_row"] = \
                f"failed: {type(e).__name__}: {e}"
    print(json.dumps(out))


def _warm_restart_rows() -> dict:
    """Persistent-compile-cache restart probe (ISSUE 4 tentpole): run the
    SAME cold-start child twice against one shared, initially-empty cache
    dir. Child 1 is a true cold start (empty cache); child 2 models a
    worker restart — same shapes, populated cache — so the delta is
    exactly what the persistent cache saves across restarts.

    `warmup` here is the cold-start OVERHEAD: (pipeline build + first
    run) - one steady-state run, i.e. everything a restart pays before
    serving at steady throughput. Both children measure it identically,
    so warm_restart_warmup_s / warm_restart_cold_warmup_s is a clean
    ratio (< 0.5 = the cache halves restart warmup)."""
    import shutil
    import subprocess
    import tempfile

    timeout_s = _row_timeout("warm_restart", 900.0)
    cache_dir = tempfile.mkdtemp(prefix="bench_xla_cache_")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               CHIASWARM_COMPILE_CACHE_DIR=cache_dir)
    out: dict = {}
    runs = []
    try:
        for leg in ("cold", "warm_restart"):
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--row", "warm-restart"],
                    timeout=timeout_s, capture_output=True, text=True, env=env,
                )
                sys.stderr.write(proc.stderr[-1500:] + "\n")
                row = _parse_last_json(proc.stdout)
                if row is None or "warmup_s" not in row:
                    out[f"warm_restart_{leg}_row"] = \
                        f"failed: no JSON (rc={proc.returncode})"
                    return out
                runs.append(row)
            except subprocess.TimeoutExpired:
                out[f"warm_restart_{leg}_row"] = \
                    f"failed: timeout after {timeout_s:.0f}s"
                return out
        cold, warm = runs
        out["warm_restart_cold_warmup_s"] = cold["warmup_s"]
        out["warm_restart_warmup_s"] = warm["warmup_s"]
        if cold["warmup_s"] > 0:
            out["warm_restart_ratio"] = round(
                warm["warmup_s"] / cold["warmup_s"], 3)
        out["warm_restart_detail"] = {
            "cold": cold, "warm": warm,
            "cache_entries": len(os.listdir(cache_dir)),
        }
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    return out


def run_warm_restart_row() -> None:
    """Child for the warm-restart probe: one cold start of the tiny smoke
    pipeline against whatever CHIASWARM_COMPILE_CACHE_DIR holds, timing
    pipeline build, first run, and a steady-state run separately.
    min_compile_time 0.0 so every program of the tiny pipeline persists
    (the worker's 1.0 s floor is a spam guard, not a correctness knob)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache(min_compile_time_s=0.0)

    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    size, steps, batch = 64, 4, 4
    t0 = time.perf_counter()
    pipe = SDPipeline("test/tiny-sd", chipset=ChipSet(jax.devices()),
                      allow_random_init=True)
    build_s = time.perf_counter() - t0
    kw = dict(prompt="warm restart probe", height=size, width=size,
              num_inference_steps=steps, num_images_per_prompt=batch,
              scheduler_type="EulerDiscreteScheduler")
    t0 = time.perf_counter()
    pipe.run(rng=jax.random.key(0), **kw)
    first_run_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    pipe.run(rng=jax.random.key(1), **kw)
    steady_run_s = time.perf_counter() - t0
    print(json.dumps({
        "build_s": round(build_s, 2),
        "first_run_s": round(first_run_s, 2),
        "steady_run_s": round(steady_run_s, 2),
        # the restart cost: everything before steady-state throughput
        "warmup_s": round(build_s + first_run_s - steady_run_s, 2),
        "size": size, "steps": steps, "batch": batch,
    }))


def _placement_row_subprocess() -> dict:
    """Residency-aware placement smoke on a 4-virtual-device / 2-slice
    allocator (same virtual-chip trick as the batched CPU row): drives
    the REAL dispatch-board claim path (batching.BatchScheduler.claim +
    SliceAllocator.acquire_for + the residency map) through a cold ->
    affinity -> steal sequence and reports swarm_placement_total."""
    import subprocess

    timeout_s = _row_timeout("placement_cpu", 300.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--row", "placement-cpu"],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-1500:] + "\n")
        row = _parse_last_json(proc.stdout)
        if row is None:
            row = {"placement_row": f"failed: no JSON (rc={proc.returncode})"}
    except subprocess.TimeoutExpired:
        row = {"placement_row": f"failed: timeout after {timeout_s:.0f}s"}
    return row


def run_placement_cpu_row() -> None:
    """Child for the placement smoke: 2 slices, one model family. The
    scenario itself lives in tools/placement_stats.py (_inprocess_claims
    — pipeline LOADs emulated via note_resident, exactly what the
    registry records after a build) so the bench row and the operator
    tool can never diverge; this child only formats the JSON row."""
    import asyncio
    import importlib.util

    import jax

    jax.config.update("jax_platforms", "cpu")

    from chiaswarm_tpu import telemetry

    tool_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "tools", "placement_stats.py")
    spec = importlib.util.spec_from_file_location("placement_stats", tool_path)
    tool = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("placement_stats", tool)
    spec.loader.exec_module(tool)

    seq = asyncio.run(tool._inprocess_claims())
    # one aggregation implementation: the same summary the operator tool
    # prints, computed from the same registry rendering
    summary = tool.placement_summary(
        tool.parse_metrics(telemetry.REGISTRY.render()))
    print(json.dumps({
        "placement_sequence": seq,
        "placement_total": summary["placements"],
        "affinity_hit_rate": summary["affinity_hit_rate"],
        "steals": summary["steals"],
        "placement_slices": 2,
    }))


def _hive_e2e_row_subprocess() -> dict:
    """The first bench number covering the WHOLE swarm loop: an embedded
    hive coordinator (chiaswarm_tpu/hive_server) in a child process and a
    pristine worker in a grandchild, talking over real loopback sockets —
    submit -> queue -> residency-aware dispatch -> lease -> denoise ->
    POST /results -> idempotent ACK. Reports jobs/s, hive-side queue-wait
    p50/p95, and the redelivery count (0 in a healthy run), then a
    preemption-tolerance phase (ISSUE 18): a checkpoint-armed worker
    killed mid-denoise, a second worker resuming from the checkpoint —
    resume_saved_steps_ratio + the preview artifact count."""
    import subprocess

    timeout_s = _row_timeout("hive_e2e", 900.0)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
    ).strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--row", "hive-e2e-cpu"],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        row = _parse_last_json(proc.stdout)
        if row is None:
            row = {"hive_e2e_row": f"failed: no JSON (rc={proc.returncode})"}
    except subprocess.TimeoutExpired:
        row = {"hive_e2e_row": f"failed: timeout after {timeout_s:.0f}s"}
    return row


def _hive_row_subprocess(row: str, key: str, timeout_default: float,
                         extra_env: dict | None = None) -> dict:
    """Shared parent wrapper for the hive robustness rows (restart,
    failover): spawn the child row, tail its stderr, parse its JSON."""
    import subprocess

    timeout_s = _row_timeout(row.replace("-", "_"), timeout_default)
    env = dict(os.environ, **(extra_env or {}))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--row", row],
            timeout=timeout_s, capture_output=True, text=True, env=env,
        )
        sys.stderr.write(proc.stderr[-2000:] + "\n")
        parsed = _parse_last_json(proc.stdout)
        if parsed is None:
            parsed = {key: f"failed: no JSON (rc={proc.returncode})"}
    except subprocess.TimeoutExpired:
        parsed = {key: f"failed: timeout after {timeout_s:.0f}s"}
    return parsed


def _hive_restart_row_subprocess() -> dict:
    """Hive-restart durability row (child: run_hive_restart_row); no jax
    anywhere in this path, so it is cheap next to the e2e row."""
    return _hive_row_subprocess("hive-restart", "hive_restart_row", 180.0)


def run_hive_restart_row() -> None:
    """Child for the durability row: a hive subprocess (WAL on) accepts N
    jobs and one simulated worker lease, dies by SIGKILL, and a second
    subprocess over the same $SDAAS_ROOT must answer for every job.
    Reports wall-clock from respawn to full verification and the number
    of jobs the restart lost (the acceptance bar is exactly 0)."""
    import asyncio
    import socket
    import subprocess
    import tempfile

    n_jobs = int(os.environ.get("BENCH_HIVE_RESTART_JOBS", "64"))
    repo = os.path.dirname(os.path.abspath(__file__))
    token = "bench-hive-restart"

    async def scenario(root: str) -> dict:
        import aiohttp

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        env = dict(os.environ, SDAAS_ROOT=root, SDAAS_TOKEN=token,
                   CHIASWARM_HIVE_PORT=str(port),
                   CHIASWARM_HIVE_QUEUE_DEPTH_LIMIT="0",
                   PYTHONPATH=repo + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        uri = f"http://127.0.0.1:{port}"
        headers = {"Authorization": f"Bearer {token}",
                   "Content-type": "application/json"}

        def spawn() -> subprocess.Popen:
            return subprocess.Popen(
                [sys.executable, "-m", "chiaswarm_tpu.hive_server"],
                cwd=repo, env=env,
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)

        async def wait_up(session) -> None:
            for _ in range(300):
                try:
                    async with session.get(f"{uri}/healthz") as r:
                        if r.status in (200, 503):
                            return
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.05)
            raise TimeoutError("hive subprocess never answered /healthz")

        procs = [spawn()]
        try:
            async with aiohttp.ClientSession() as session:
                await wait_up(session)
                for i in range(n_jobs):
                    job = {"id": f"bench-restart-{i}", "workflow": "echo",
                           "model_name": "none", "prompt": f"durability {i}",
                           "priority": ("interactive", "default",
                                        "batch")[i % 3]}
                    async with session.post(f"{uri}/api/jobs",
                                            data=json.dumps(job),
                                            headers=headers) as r:
                        if r.status != 200:
                            raise RuntimeError(
                                f"submit {i} failed: {r.status}")
                # one job leased to a worker that dies with the hive —
                # recovery must keep the lease attribution too
                async with session.get(
                        f"{uri}/api/work",
                        params={"worker_version": "0.1.0",
                                "worker_name": "bench-doomed"},
                        headers=headers) as r:
                    leased = [j["id"] for j in (await r.json())["jobs"]]

                procs[0].kill()
                procs[0].wait()
                t0 = time.monotonic()
                procs.append(spawn())
                await wait_up(session)
                lost = 0
                recovered_leased = 0
                for i in range(n_jobs):
                    async with session.get(
                            f"{uri}/api/jobs/bench-restart-{i}",
                            headers=headers) as r:
                        if r.status != 200:
                            lost += 1
                            continue
                        status = await r.json()
                    if status["status"] not in ("queued", "leased"):
                        lost += 1
                    elif status["id"] in leased:
                        recovered_leased += 1
                recovery_s = time.monotonic() - t0
                return {
                    "hive_restart_jobs": n_jobs,
                    "hive_restart_leased": len(leased),
                    "hive_restart_recovered_leased": recovered_leased,
                    "hive_restart_jobs_lost": lost,
                    "hive_restart_recovery_s": round(recovery_s, 3),
                }
        finally:
            for proc in procs:
                proc.kill()
                proc.wait()

    with tempfile.TemporaryDirectory(prefix="bench_hive_restart_") as root:
        print(json.dumps(asyncio.run(scenario(root))))


def _hive_failover_row_subprocess() -> dict:
    """Hive-failover availability row (child: run_hive_failover_row):
    primary + WAL-shipped standby + one in-process echo worker, primary
    killed mid-run — reports takeover_s and jobs_lost (the acceptance
    bar is exactly 0). The child needs jax (it runs a real Worker), so
    pin it to CPU."""
    return _hive_row_subprocess("hive-failover", "hive_failover_row",
                                300.0, {"JAX_PLATFORMS": "cpu"})


def run_hive_failover_row() -> None:
    """Child for the failover row: a primary HiveServer, a WAL-shipped
    StandbyHive replicating it, and one in-process Worker (echo jobs —
    no weights, no compile) holding BOTH endpoints. The backlog is
    submitted, the primary hard-stops mid-lease, and the standby must
    health-check it dead, promote itself, and serve the worker's
    failed-over polls until every job settles. `takeover_s` is
    kill -> promoted; `jobs_lost` must be 0."""
    import asyncio
    import tempfile

    os.environ["CHIASWARM_POLL_SECONDS"] = "0.1"  # read at worker import

    n_jobs = int(os.environ.get("BENCH_HIVE_FAILOVER_JOBS", "8"))

    async def scenario() -> dict:
        import chiaswarm_tpu.worker as worker_mod
        from chiaswarm_tpu.hive_server import LocalSwarm
        from chiaswarm_tpu.settings import Settings

        # the 121 s production poll-error backoff would dominate a row
        # whose whole point is sub-second takeover
        worker_mod.ERROR_BACKOFF_SECONDS = 2.0
        settings = Settings(
            sdaas_token="bench-failover", hive_port=0, metrics_port=0,
            hive_lease_deadline_s=2.0, hive_max_redeliveries=3,
            hive_failover_grace_s=0.5, hive_replication_poll_s=0.1)
        swarm = LocalSwarm(n_workers=1, chips_per_job=0, settings=settings,
                           standby=True)
        async with swarm:
            ids = [await swarm.submit(
                {"id": f"bench-fo-{i}", "workflow": "echo",
                 "model_name": "none", "prompt": f"failover {i}"})
                for i in range(n_jobs)]
            deadline = time.monotonic() + 30.0
            while not all(j in swarm.standby.server.queue.records
                          for j in ids):
                if time.monotonic() > deadline:
                    raise TimeoutError("standby never replicated the backlog")
                await asyncio.sleep(0.05)
            t0 = time.monotonic()
            await swarm.kill_primary()
            while not swarm.standby.promoted:
                if time.monotonic() - t0 > 60.0:
                    raise TimeoutError("standby never promoted")
                await asyncio.sleep(0.02)
            takeover_s = time.monotonic() - t0
            done = 0
            for job_id in ids:
                status = await swarm.wait_done(job_id, timeout=120.0,
                                               accept_failed=True)
                done += int(status["status"] == "done")
            return {
                "hive_failover_jobs": n_jobs,
                "hive_failover_jobs_lost": n_jobs - done,
                "hive_failover_takeover_s": round(takeover_s, 3),
                "hive_failover_epoch": swarm.standby.server.epoch,
                "hive_failover_worker_failovers":
                    swarm.workers[0].hive.failovers,
            }

    with tempfile.TemporaryDirectory(prefix="bench_hive_failover_") as root:
        os.environ["SDAAS_ROOT"] = root  # isolate WAL/spool/outbox
        print(json.dumps(asyncio.run(scenario())))


def run_hive_e2e_row() -> None:
    """Child for the hive e2e row. This process runs ONLY the hive
    coordinator and the submitting client (no jax work); the worker is a
    separate pristine `python -m chiaswarm_tpu.worker` subprocess wired
    up purely through env vars — exactly how an operator deploys one."""
    import asyncio
    import subprocess
    import tempfile

    n_jobs = int(os.environ.get("BENCH_HIVE_E2E_JOBS", "8"))
    repo = os.path.dirname(os.path.abspath(__file__))

    def tiny_job(i: int, tag: str) -> dict:
        return {
            "id": f"bench-{tag}-{i}",
            "workflow": "txt2img",
            "model_name": "stabilityai/stable-diffusion-2-1",
            "prompt": f"hive e2e bench {tag} {i}",
            "seed": 4000 + i,
            "height": 64,
            "width": 64,
            "num_inference_steps": 2,
            "parameters": {"test_tiny_model": True},
        }

    async def scenario(root: str) -> dict:
        import socket

        import aiohttp

        from chiaswarm_tpu import telemetry
        from chiaswarm_tpu.hive_server import HiveServer
        from chiaswarm_tpu.settings import Settings

        token = "bench-hive"
        # the lease deadline must outlast the 600 s warmup budget: a slow
        # first compile on a loaded machine would otherwise expire the
        # lease mid-run and fail test_bench's redeliveries==0 assertion.
        # max_jobs_per_poll=8 lets the gang scheduler (ISSUE 9) hand the
        # whole 8-job burst as ONE pre-batched /work reply.
        # the SLO engine on (loose objectives — the row asserts the
        # REPORT exists and carries per-class data, not that a loaded CI
        # box hits production latencies)
        hive = await HiveServer(
            Settings(sdaas_token=token, hive_port=0,
                     hive_lease_deadline_s=900.0,
                     hive_slo="default:e2e_p95<600,queue_wait_p95<600",
                     hive_slo_fast_window_s=900.0,
                     hive_max_jobs_per_poll=8), port=0).start()
        expired = telemetry.REGISTRY.get("swarm_hive_leases_expired_total")
        headers = {"Authorization": f"Bearer {token}",
                   "Content-type": "application/json"}

        # a real (loopback) worker metrics port: the embed-cache hit
        # rate lives in the worker SUBPROCESS's registry and is only
        # observable the way an operator would see it — a /metrics scrape
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            metrics_port = probe.getsockname()[1]
        worker_env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            SDAAS_ROOT=root,
            SDAAS_URI=hive.uri,
            SDAAS_TOKEN=token,
            SDAAS_WORKERNAME="bench-hive-worker",
            CHIASWARM_POLL_SECONDS="0.1",
            CHIASWARM_METRICS_PORT=str(metrics_port),
            # chunked denoise (ISSUE 10): the cancel_reclaim_s phase
            # needs chunk boundaries to abort at; the 2-step burst jobs
            # run as a single 2-step chunk, so their numbers are
            # unchanged in practice
            CHIASWARM_DENOISE_CHUNK_STEPS="2",
            PYTHONPATH=repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        worker = subprocess.Popen(
            [sys.executable, "-m", "chiaswarm_tpu.worker"],
            cwd=repo, env=worker_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
        )
        try:
            async with aiohttp.ClientSession() as session:

                async def submit(job: dict) -> str:
                    async with session.post(
                            f"{hive.api_uri}/jobs", headers=headers,
                            data=json.dumps(job)) as resp:
                        resp.raise_for_status()
                        return (await resp.json())["id"]

                async def wait_done(job_id: str, budget_s: float) -> dict:
                    deadline = time.monotonic() + budget_s
                    while time.monotonic() < deadline:
                        async with session.get(
                                f"{hive.api_uri}/jobs/{job_id}",
                                headers=headers) as resp:
                            status = await resp.json()
                        if status["status"] in ("done", "failed"):
                            return status
                        await asyncio.sleep(0.1)
                    raise TimeoutError(f"job {job_id} never completed")

                async def submit_burst(tag: str, count: int) -> list[str]:
                    """Queue `count` jobs as ONE burst: /work polls are
                    gated (refuse_with — the hive-side drain switch, a
                    400 the worker just backs off from) while the jobs
                    are submitted, so the whole burst is queued when the
                    next poll lands and the gang scheduler sees it
                    together — the deterministic version of 'bursty
                    multi-client traffic'."""
                    hive.refuse_with = f"queueing {tag} burst"
                    try:
                        return [await submit(tiny_job(i, tag))
                                for i in range(count)]
                    finally:
                        hive.refuse_with = None

                # warmup: the worker's first tiny burst pays pipeline
                # build + the BATCHED program's XLA compile; the timed
                # window must not include those one-off costs, so it is
                # a full same-shape gang measured (and reported) apart
                t0 = time.monotonic()
                warmup_ids = await submit_burst("warmup", n_jobs)
                warmup_deadline = time.monotonic() + 600.0
                for warmup_id in warmup_ids:
                    status = await wait_done(
                        warmup_id,
                        max(warmup_deadline - time.monotonic(), 1.0))
                    if status["status"] != "done":
                        raise RuntimeError(
                            f"warmup job failed at the hive: "
                            f"{status['error']}")
                warmup_s = time.monotonic() - t0

                t0 = time.monotonic()
                ids = await submit_burst("run", n_jobs)
                waits = []
                # one SHARED deadline for the timed phase, not 300 s per
                # job: 600 s warmup + 240 s run stays inside the parent
                # row timeout (900 s), so a slow-but-healthy run fails
                # with a per-job error here instead of a bare parent
                # TimeoutExpired that discards the stderr tail
                run_deadline = time.monotonic() + 240.0
                for job_id in ids:
                    status = await wait_done(
                        job_id, max(run_deadline - time.monotonic(), 1.0))
                    if status["status"] != "done":
                        raise RuntimeError(
                            f"job {job_id} failed: {status['error']}")
                    waits.append(float(status["queue_wait_s"] or 0.0))
                wall_s = time.monotonic() - t0

                # trace_e2e: every settled job must answer with a
                # COMPLETE, gap-free timeline — hive lifecycle events,
                # placement outcome, attributed queue-wait gap, and the
                # worker's stage spans merged from the envelope
                # (trace_missing is the same checker the durability
                # tests pin)
                from chiaswarm_tpu.hive_server.trace import trace_missing

                traced, incomplete = 0, []
                gang_sizes = []  # timed jobs only: the gang_rate datum
                for job_id in [*warmup_ids, *ids]:
                    async with session.get(
                            f"{hive.api_uri}/jobs/{job_id}/trace",
                            headers=headers) as resp:
                        if resp.status != 200:
                            incomplete.append(
                                f"{job_id}: trace HTTP {resp.status}")
                            continue
                        trace = await resp.json()
                    missing = trace_missing(trace)
                    if missing:
                        incomplete.append(f"{job_id}: {missing}")
                    else:
                        traced += 1
                    if job_id in ids:
                        # the LAST dispatch is the one that produced the
                        # settle; its gang_size (stamped by queue.take,
                        # WAL-durable) says whether the job arrived
                        # pre-batched
                        dispatches = [e for e in trace.get("events", [])
                                      if e.get("event") == "dispatch"]
                        gang_sizes.append(int(
                            dispatches[-1].get("gang_size", 1))
                            if dispatches else 1)

                # embed-cache hit rate, scraped from the worker
                # subprocess's /metrics exactly as an operator would.
                # Retried: the ephemeral port was probed bind-then-close,
                # so a (rare) collision or a slow metrics-app start must
                # read as a visible scrape failure, not a silent 0.0
                embed_hits = embed_misses = 0.0
                for attempt in range(3):
                    try:
                        async with session.get(
                                "http://127.0.0.1:"
                                f"{metrics_port}/metrics") as resp:
                            exposition = await resp.text()
                        for line in exposition.splitlines():
                            if line.startswith(
                                    'swarm_embed_cache_total{event="hit"}'):
                                embed_hits = float(line.rsplit(None, 1)[-1])
                            elif line.startswith(
                                    'swarm_embed_cache_total'
                                    '{event="miss"}'):
                                embed_misses = float(
                                    line.rsplit(None, 1)[-1])
                        break
                    except Exception as e:  # noqa: BLE001 — report it
                        if attempt == 2:
                            incomplete.append(
                                f"worker metrics scrape failed: {e}")
                        else:
                            await asyncio.sleep(1.0)

                # --- cancellation phase (ISSUE 10): wall clock from the
                # cancel POST to the slice reporting free, asserted
                # against a measured full pass of the same shape ---
                async def busy_slices() -> float:
                    async with session.get(
                            "http://127.0.0.1:"
                            f"{metrics_port}/metrics") as resp:
                        for line in (await resp.text()).splitlines():
                            if line.startswith("swarm_slices_busy "):
                                return float(line.rsplit(None, 1)[-1])
                    return 0.0

                def long_job(tag: str) -> dict:
                    # a pass long enough to cancel INSIDE: many chunk
                    # boundaries at denoise_chunk_steps=2, short enough
                    # that the two reference passes stay cheap
                    return dict(tiny_job(0, tag), num_inference_steps=32)

                # two reference passes: the first pays the fresh 48-step
                # chunk-program compiles, the second measures the warm
                # full-pass wall the reclaim must beat
                await wait_done(await submit(long_job("cancel-warm")), 600.0)
                t0 = time.monotonic()
                await wait_done(await submit(long_job("cancel-ref")), 240.0)
                full_pass_s = time.monotonic() - t0

                victim = await submit(long_job("cancel-victim"))
                # cancel once the pass is actually ON the slice
                deadline = time.monotonic() + 60.0
                while time.monotonic() < deadline:
                    if await busy_slices() >= 1:
                        break
                    await asyncio.sleep(0.02)
                t0 = time.monotonic()
                async with session.post(
                        f"{hive.api_uri}/jobs/{victim}/cancel",
                        headers=headers) as resp:
                    cancel_ack = await resp.json()
                reclaim_s = None
                deadline = time.monotonic() + max(2 * full_pass_s, 30.0)
                while time.monotonic() < deadline:
                    if await busy_slices() == 0:
                        reclaim_s = time.monotonic() - t0
                        break
                    await asyncio.sleep(0.02)
                async with session.get(f"{hive.api_uri}/jobs/{victim}",
                                       headers=headers) as resp:
                    victim_status = (await resp.json())["status"]

                # --- fleet accounting & SLOs (ISSUE 11): the ledger's
                # attributed chip-seconds over the independently summed
                # executing spans of every settled job (from each
                # envelope's own stage timings) — anything the ledger
                # dropped shows up as a ratio below 1.0 ---
                from chiaswarm_tpu.hive_server.accounting import (
                    chip_seconds_of,
                )

                settled_ids = [*warmup_ids, *ids,
                               "bench-cancel-warm-0", "bench-cancel-ref-0"]
                if victim_status == "done":  # the raced no-op side
                    settled_ids.append(victim)
                executing_span_s = 0.0
                # cost plane (ISSUE 17): independently sum every settled
                # envelope's pipeline_config.cost stamp so the ledger's
                # /usage flops can be cross-checked against the source
                envelope_flops = 0
                cost_stamped = 0
                mfu_samples = []
                for job_id in settled_ids:
                    async with session.get(
                            f"{hive.api_uri}/jobs/{job_id}",
                            headers=headers) as resp:
                        st = await resp.json()
                    pc = ((st.get("result") or {}).get(
                        "pipeline_config") or {})
                    span = chip_seconds_of(pc.get("timings"))
                    if span:
                        executing_span_s += span
                    cost = pc.get("cost")
                    if isinstance(cost, dict):
                        cost_stamped += 1
                        if isinstance(cost.get("flops"), int):
                            envelope_flops += max(cost["flops"], 0)
                        if cost.get("mfu") is not None:
                            mfu_samples.append(cost["mfu"])
                async with session.get(f"{hive.api_uri}/usage",
                                       headers=headers) as resp:
                    usage = await resp.json()
                async with session.get(f"{hive.api_uri}/slo",
                                       headers=headers) as resp:
                    slo_report = await resp.json()

                # --- preemption tolerance (ISSUE 18): a checkpoint-armed
                # worker is SIGKILL'd mid-denoise past a shipped
                # chunk-boundary checkpoint; the lease is force-expired
                # and a second resume-capable worker must finish the
                # pass from the checkpointed step via the redelivery's
                # `resume` offer. Reports the fraction of the pass the
                # resume SAVED over a naive full redelivery, plus the
                # progressive-preview artifact count. The main worker
                # ran WITHOUT the checkpoint knobs, so every number
                # above is from the classic (byte-identical) path; its
                # redelivery count is snapshotted here — the forced
                # expiry below belongs to this phase alone ---
                redeliveries_main = int(expired.value()) if expired else 0
                worker.terminate()  # the resume workers replace it
                try:
                    await asyncio.to_thread(worker.wait, 30)
                except subprocess.TimeoutExpired:
                    worker.kill()

                def spawn_resume_worker(name: str) -> subprocess.Popen:
                    # same env (shared $SDAAS_ROOT -> warm persistent
                    # compile cache from the main phase) + the ISSUE 18
                    # knobs: checkpoint every chunk, preview every 4th
                    env2 = dict(worker_env, SDAAS_WORKERNAME=name,
                                CHIASWARM_METRICS_PORT="0",
                                CHIASWARM_CHECKPOINT_EVERY_CHUNKS="1",
                                CHIASWARM_PREVIEW_EVERY_CHUNKS="4")
                    return subprocess.Popen(
                        [sys.executable, "-m", "chiaswarm_tpu.worker"],
                        cwd=repo, env=env2, stdout=subprocess.DEVNULL,
                        stderr=subprocess.STDOUT)

                resume_steps = 32  # the cancel jobs' shape: warm compile
                doomed = spawn_resume_worker("bench-resume-doomed")
                heir = None
                try:
                    resume_id = await submit(dict(
                        tiny_job(0, "resume"),
                        num_inference_steps=resume_steps))

                    async def checkpoint_shipped() -> bool:
                        async with session.get(
                                f"{hive.api_uri}/jobs/{resume_id}/trace",
                                headers=headers) as resp:
                            if resp.status != 200:
                                return False
                            tr = await resp.json()
                        return any(e.get("event") == "checkpoint"
                                   for e in tr.get("events", []))

                    deadline = time.monotonic() + 600.0
                    while not await checkpoint_shipped():
                        if time.monotonic() > deadline:
                            raise TimeoutError(
                                "resume phase: no checkpoint within 600s")
                        await asyncio.sleep(0.05)
                    # checkpoint durable at step >=2 of 32: the kill
                    # lands mid-denoise, never after the result POST
                    doomed.kill()
                    await asyncio.to_thread(doomed.wait)
                    # the row's 900s lease would stall the phase: expire
                    # it NOW (the hive is in-process) so the reaper
                    # redelivers on its next ~1s tick
                    lease = hive.leases.get(resume_id)
                    if lease is not None:
                        lease.expires_at = hive.queue.clock.mono() - 1.0
                    heir = spawn_resume_worker("bench-resume-heir")
                    resume_status = await wait_done(resume_id, 240.0)
                    if resume_status["status"] != "done":
                        raise RuntimeError(
                            "resume job failed: "
                            f"{resume_status['error']}")
                finally:
                    for proc in (doomed, heir):
                        if proc is not None and proc.poll() is None:
                            proc.terminate()
                            try:
                                await asyncio.to_thread(proc.wait, 30)
                            except subprocess.TimeoutExpired:
                                proc.kill()

                resumed_stamp = ((resume_status.get("result") or {})
                                 .get("pipeline_config")
                                 or {}).get("resumed") or {}
                resume_from_step = int(resumed_stamp.get("from_step", 0))
                resume_recomputed = int(resumed_stamp.get(
                    "recomputed_steps", resume_steps))
                async with session.get(
                        f"{hive.api_uri}/jobs/{resume_id}/trace",
                        headers=headers) as resp:
                    resume_events = [e.get("event") for e in
                                     (await resp.json()).get("events", [])]

                # --- stage-graph micro-serving (ISSUE 20): the txt2img
                # chain served as a hive-visible DAG (encode -> denoise
                # -> decode), with stage-typed placement split across a
                # two-worker fleet. The chip worker runs stage_workers=0
                # so its `auto` roles advertise ONLY the chip stages;
                # every encode/decode MUST therefore land on the host
                # worker (the offload datum is deterministic, not a
                # race). The same N workflows run twice: strictly
                # sequentially (submit -> drain -> submit) and as one
                # gated burst — the wall ratio is the cross-pass
                # pipelining win, and the pipelined traces yield the
                # wall-clock seconds decode-of-N actually spent inside
                # denoise-of-N+1 ---
                n_wf = int(os.environ.get("BENCH_DAG_WORKFLOWS", "4"))

                def dag_workflow(i: int, tag: str) -> dict:
                    wf = tiny_job(i, f"dag-{tag}")
                    wf["id"] = f"bench-dag-{tag}-{i}"
                    return wf

                async def submit_wf(payload: dict) -> str:
                    async with session.post(
                            f"{hive.api_uri}/workflows", headers=headers,
                            data=json.dumps(payload)) as resp:
                        resp.raise_for_status()
                        return (await resp.json())["id"]

                async def wait_wf(wf_id: str, budget_s: float) -> dict:
                    deadline = time.monotonic() + budget_s
                    while time.monotonic() < deadline:
                        async with session.get(
                                f"{hive.api_uri}/workflows/{wf_id}",
                                headers=headers) as resp:
                            status = await resp.json()
                        if status["status"] in (
                                "done", "failed", "cancelled"):
                            return status
                        await asyncio.sleep(0.05)
                    raise TimeoutError(f"workflow {wf_id} never completed")

                # both dag workers poll at 0.5s, NOT the 0.1s the main
                # phase tightens to: dispatch latency is the component
                # cross-pass pipelining hides, and at 0.1s it is
                # vanishingly small next to a CPU-box denoise — the
                # sequential leg would measure ~1.0x on noise. 0.5s
                # weights it realistically (production cadence is
                # coarser still) and applies identically to both legs.
                chip_env = dict(
                    worker_env, SDAAS_WORKERNAME="bench-dag-chip",
                    CHIASWARM_METRICS_PORT="0",
                    CHIASWARM_POLL_SECONDS="0.5",
                    # no stage lane -> `auto` advertises chip stages only
                    CHIASWARM_STAGE_WORKERS="0",
                    # batch-1 denoise passes: the 2-step chunk program is
                    # warm from the cancel phase, so neither timed leg
                    # pays a mid-measurement compile
                    SDAAS_MAX_COALESCE="1", SDAAS_BATCH_LINGER_MS="0")
                host_env = dict(
                    worker_env, SDAAS_WORKERNAME="bench-dag-host",
                    CHIASWARM_METRICS_PORT="0",
                    CHIASWARM_POLL_SECONDS="0.5",
                    CHIASWARM_STAGE_ROLES=(
                        "encode,decode,postprocess,stitch,caption"))
                dag_workers = [subprocess.Popen(
                    [sys.executable, "-m", "chiaswarm_tpu.worker"],
                    cwd=repo, env=env2, stdout=subprocess.DEVNULL,
                    stderr=subprocess.STDOUT)
                    for env2 in (chip_env, host_env)]
                dag_status: dict[str, dict] = {}
                try:
                    # warmup graph: pipeline build + any residual compile
                    warm_id = await submit_wf(dag_workflow(0, "warm"))
                    dag_status[warm_id] = await wait_wf(warm_id, 600.0)

                    t0 = time.monotonic()
                    for i in range(n_wf):
                        wf_id = await submit_wf(dag_workflow(i, "seq"))
                        dag_status[wf_id] = await wait_wf(wf_id, 240.0)
                    dag_seq_wall = time.monotonic() - t0

                    hive.refuse_with = "queueing dag burst"
                    try:
                        pipe_ids = [await submit_wf(dag_workflow(i, "pipe"))
                                    for i in range(n_wf)]
                    finally:
                        hive.refuse_with = None
                    t0 = time.monotonic()
                    for wf_id in pipe_ids:
                        dag_status[wf_id] = await wait_wf(wf_id, 240.0)
                    dag_pipe_wall = time.monotonic() - t0
                finally:
                    for proc in dag_workers:
                        proc.terminate()
                    for proc in dag_workers:
                        try:
                            await asyncio.to_thread(proc.wait, 30)
                        except subprocess.TimeoutExpired:
                            proc.kill()

                encode_total = encode_offloaded = 0
                for wf_id, st in dag_status.items():
                    if st["status"] != "done":
                        raise RuntimeError(
                            f"dag workflow {wf_id} ended {st['status']}")
                    for s in st["stages"]:
                        if s["stage"] == "encode":
                            encode_total += 1
                            if s["worker"] == "bench-dag-host":
                                encode_offloaded += 1

                # per-workflow dispatch->settle windows from the merged
                # parent traces (every event carries its stage name);
                # the overlap datum is the summed intersection of each
                # decode window with every OTHER workflow's denoise
                dag_spans: list[dict] = []
                for wf_id in pipe_ids:
                    async with session.get(
                            f"{hive.api_uri}/workflows/{wf_id}/trace",
                            headers=headers) as resp:
                        tr = await resp.json()
                    missing = trace_missing(tr)
                    if missing:
                        incomplete.append(f"dag {wf_id}: {missing}")
                    windows: dict[str, list[float | None]] = {}
                    for e in tr.get("events", []):
                        stage = e.get("stage")
                        event = e.get("event")
                        if stage and event in ("dispatch", "settle"):
                            windows.setdefault(stage, [None, None])[
                                0 if event == "dispatch" else 1
                            ] = float(e.get("wall", 0.0))
                    dag_spans.append(windows)

                def _window_overlap_s(a, b) -> float:
                    if None in (a or [None]) or None in (b or [None]):
                        return 0.0
                    return max(min(a[1], b[1]) - max(a[0], b[0]), 0.0)

                dag_overlap_s = sum(
                    _window_overlap_s(wa.get("decode"), wb.get("denoise"))
                    for i, wa in enumerate(dag_spans)
                    for j, wb in enumerate(dag_spans) if i != j)

            waits.sort()
            pre_batched = sum(1 for s in gang_sizes if s >= 2)
            gang_sizes.sort()
            embed_total = embed_hits + embed_misses
            return {
                "trace_e2e_jobs": len(warmup_ids) + len(ids),
                "trace_e2e_complete": traced,
                "trace_e2e_incomplete": incomplete,
                "hive_e2e_jobs_per_s": round(n_jobs / wall_s, 3),
                "hive_e2e_jobs": n_jobs,
                "hive_e2e_wall_s": round(wall_s, 2),
                "hive_e2e_warmup_s": round(warmup_s, 2),
                "hive_e2e_queue_wait_p50_s": waits[len(waits) // 2],
                "hive_e2e_queue_wait_p95_s": waits[
                    int(0.95 * (len(waits) - 1))],
                "hive_e2e_redeliveries": redeliveries_main,
                # hive-side coalesced dispatch (ISSUE 9): fraction of the
                # timed burst arriving pre-batched, and the size spread
                "gang_rate": round(
                    pre_batched / len(gang_sizes), 3) if gang_sizes else 0.0,
                "gang_size_p50": (
                    gang_sizes[len(gang_sizes) // 2] if gang_sizes else 0),
                "embed_cache_hit_rate": round(
                    embed_hits / embed_total, 3) if embed_total else 0.0,
                "embed_cache_hits": int(embed_hits),
                "embed_cache_misses": int(embed_misses),
                # cancellation & deadlines (ISSUE 10): cancel POST ->
                # slice free, vs the warm full pass it interrupted.
                # cancel_raced=True means the pass finished before the
                # cancel landed (the no-op side of the pinned race)
                "cancel_reclaim_s": (round(reclaim_s, 3)
                                     if reclaim_s is not None else None),
                "cancel_full_pass_s": round(full_pass_s, 3),
                "cancel_victim_status": victim_status,
                "cancel_raced": not bool(cancel_ack.get("cancelled")),
                # fleet accounting & SLOs (ISSUE 11): tenant-attributed
                # chip-seconds over summed executing spans (>= 0.95 in
                # test_bench = nothing silently dropped), and whether
                # the SLO engine reported real per-class data
                "usage_accounted_ratio": round(
                    usage["totals"]["chip_seconds"] / executing_span_s, 4)
                if executing_span_s > 0 else 0.0,
                "usage_chip_seconds": usage["totals"]["chip_seconds"],
                "usage_settled_jobs": usage["totals"]["jobs"],
                "usage_fallback_jobs": usage["totals"]["fallback_jobs"],
                # serving-path cost plane (ISSUE 17): fleet TFLOP/s over
                # the summed executing spans, the ledger's flops against
                # the independent envelope-stamp sum (~1.0 = nothing
                # dropped), and MFU (null on CPU — no peak entry)
                "hive_e2e_fleet_tflops": round(
                    envelope_flops / executing_span_s / 1e12, 4)
                if executing_span_s > 0 else None,
                "hive_e2e_mfu": max(mfu_samples) if mfu_samples else None,
                "hive_e2e_envelope_flops": envelope_flops,
                "hive_e2e_cost_stamped_jobs": cost_stamped,
                "usage_flops": usage["totals"].get("flops", 0),
                "usage_flops_ratio": round(
                    usage["totals"].get("flops", 0) / envelope_flops, 4)
                if envelope_flops > 0 else 0.0,
                "slo_report_present": bool(
                    slo_report.get("enabled")
                    and slo_report.get("classes", {}).get("default", {})
                    .get("objectives")),
                # preemption tolerance (ISSUE 18): resume-on-redelivery
                # skipped `from_step` of the pass's steps; a naive
                # redelivery recomputes every one. Previews are counted
                # from the trace timeline — terminal states clear the
                # `partial` disposition, the timeline keeps the events
                "hive_e2e_resume_saved_steps_ratio": round(
                    resume_from_step
                    / max(resume_from_step + resume_recomputed, 1), 3),
                "hive_e2e_resume_from_step": resume_from_step,
                "hive_e2e_resume_recomputed_steps": resume_recomputed,
                "hive_e2e_resume_offers":
                    resume_events.count("resume_offer"),
                "hive_e2e_preview_artifacts":
                    resume_events.count("preview"),
                # stage-graph micro-serving (ISSUE 20): the same N-deep
                # DAG burst pipelined vs strictly sequential, the
                # wall-clock seconds decode-of-N ran inside another
                # pass's denoise, and the (deterministic, by stage-typed
                # placement) fraction of encode stages the chip-less
                # host worker served
                "dag_pipeline_workflows": n_wf,
                "dag_sequential_wall_s": round(dag_seq_wall, 2),
                "dag_pipelined_wall_s": round(dag_pipe_wall, 2),
                "dag_overlap_speedup": round(
                    dag_seq_wall / dag_pipe_wall, 3)
                if dag_pipe_wall > 0 else None,
                "dag_decode_denoise_overlap_s": round(dag_overlap_s, 3),
                "dag_encode_stages": encode_total,
                "dag_encode_offload_rate": round(
                    encode_offloaded / encode_total, 3)
                if encode_total else 0.0,
            }
        finally:
            worker.terminate()  # SIGTERM -> graceful drain
            try:
                worker.wait(timeout=30)
            except subprocess.TimeoutExpired:
                worker.kill()
            await hive.stop()

    with tempfile.TemporaryDirectory(prefix="bench_hive_") as root:
        os.environ["SDAAS_ROOT"] = root  # hive spool isolation
        print(json.dumps(asyncio.run(scenario(root))))


def run_batched_cpu_row() -> None:
    """Child for the CPU batched row: tiny model on however many virtual
    CPU devices the parent's XLA_FLAGS carved out, serving ONE slice."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    _enable_compile_cache()
    chips = jax.devices()

    from chiaswarm_tpu.chips.device import ChipSet
    from chiaswarm_tpu.pipelines.stable_diffusion import SDPipeline

    pipe = SDPipeline("test/tiny-sd", chipset=ChipSet(chips),
                      allow_random_init=True)
    rows = _batched_rows(pipe, len(chips))
    rows["batched_slice_devices"] = len(chips)
    print(json.dumps(rows))


def _batched_rows(pipe, n_chips: int, size: int = 64, steps: int = 4) -> dict:
    """Cross-job micro-batching ladder: images/sec/chip for ONE padded
    run_batched pass at coalesce factors 1/2/4 (each request batch-1, the
    hive's dominant job shape), plus the factor-4/factor-1 speedup — the
    number the batching scheduler's linger window buys."""
    import jax

    out: dict = {}
    rates: dict[int, float] = {}
    for factor in (1, 2, 4):
        requests = [
            dict(prompt=f"bench coalesce {i}", negative_prompt="",
                 num_images_per_prompt=1, rng=jax.random.key(100 + i))
            for i in range(factor)
        ]
        shared = dict(height=size, width=size, num_inference_steps=steps,
                      guidance_scale=7.5,
                      scheduler_type="EulerDiscreteScheduler")
        try:
            pipe.run_batched(requests, **shared)  # compile
            times = []
            last = None
            for _ in range(3):
                t0 = time.perf_counter()
                last = pipe.run_batched(requests, **shared)
                times.append(time.perf_counter() - t0)
            p50 = sorted(times)[1]
            rates[factor] = factor / p50 / n_chips
            out[f"batched_txt2img_x{factor}_img_per_sec_per_chip"] = round(
                rates[factor], 4)
            out[f"batched_txt2img_x{factor}_p50_pass_s"] = round(p50, 3)
            # shared-pass span timings (telemetry.Span), last timed run
            out[f"batched_txt2img_x{factor}_stage_timings"] = dict(
                last[0][1].get("timings", {}))
        except Exception as e:
            sys.stderr.write(
                f"batched row x{factor} failed: {type(e).__name__}: {e}\n")
            out[f"batched_txt2img_x{factor}_row"] = \
                f"failed: {type(e).__name__}: {e}"
    if rates.get(1) and rates.get(4):
        out["batched_coalesce4_speedup"] = round(rates[4] / rates[1], 3)
    return out


def _quick_rate(pipe, kw) -> tuple[float, float]:
    import jax

    pipe.run(rng=jax.random.key(0), prompt="bench", **kw)  # compile
    times = []
    for i in range(3):
        t0 = time.perf_counter()
        pipe.run(rng=jax.random.key(i + 1), prompt="bench", **kw)
        times.append(time.perf_counter() - t0)
    p50 = sorted(times)[1]  # true median of 3
    return kw["num_images_per_prompt"] / p50, p50


def run_config(pipe, size: int, steps: int, batch: int):
    import jax

    kw = dict(
        prompt="a photograph of an astronaut riding a horse on mars",
        negative_prompt="blurry, low quality",
        height=size,
        width=size,
        num_inference_steps=steps,
        num_images_per_prompt=batch,
        scheduler_type="EulerDiscreteScheduler",
    )

    # warmup: compile + first run
    t0 = time.perf_counter()
    pipe.run(rng=jax.random.key(0), **kw)
    warmup_s = time.perf_counter() - t0
    sys.stderr.write(f"warmup (incl. compile): {warmup_s:.1f}s\n")

    # VERDICT r04 #8: one real profiler trace to confirm the analytic MFU
    # denominator (models/flops.py). Traces only the middle timed run so
    # the p50 sample stays clean.
    profile_dir = os.environ.get("BENCH_PROFILE_DIR", "")

    job_times, denoise_times, configs = [], [], []
    runs = 3
    config = {}
    for i in range(runs):
        t0 = time.perf_counter()
        if profile_dir and i == 1:
            with jax.profiler.trace(profile_dir):
                _, config = pipe.run(rng=jax.random.key(i + 1), **kw)
        else:
            _, config = pipe.run(rng=jax.random.key(i + 1), **kw)
        job_times.append(time.perf_counter() - t0)
        denoise_times.append(config["timings"]["denoise_decode_s"])
        configs.append(config)
        sys.stderr.write(
            f"run {i}: {job_times[-1]:.2f}s job, "
            f"{denoise_times[-1]:.2f}s denoise+decode\n"
        )

    order = sorted(range(runs), key=lambda i: job_times[i])
    mid = order[runs // 2]
    p50 = job_times[mid]
    extra = {"denoise_fraction": round(denoise_times[mid] / p50, 3),
             "warmup_s": round(warmup_s, 1),
             # per-stage breakdown of the MEDIAN run, sourced from the same
             # telemetry spans that feed /metrics (text_encode/compile/
             # denoise(+decode) keys from pipelines, decode from workflows)
             "stage_timings": dict(configs[mid].get("timings", {}))}
    peak = peak_tflops(jax.devices()[0])
    if peak and config.get("unet_tflops"):
        # MFU over the denoise+decode program (UNet FLOPs only — VAE and
        # ControlNet are excluded, so this is a conservative floor). The
        # batch shards over the mesh, so peak scales with chip count.
        extra["unet_mfu"] = round(
            config["unet_tflops"]
            / denoise_times[mid]
            / (peak * len(jax.devices())),
            4,
        )
    return batch / p50, p50, batch, extra


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--row":
        if sys.argv[2] == "batched-cpu":
            run_batched_cpu_row()
        elif sys.argv[2] == "lora-coalesce-cpu":
            run_lora_coalesce_row()
        elif sys.argv[2] == "sharded-cpu":
            run_sharded_cpu_row()
        elif sys.argv[2] == "warm-restart":
            run_warm_restart_row()
        elif sys.argv[2] == "placement-cpu":
            run_placement_cpu_row()
        elif sys.argv[2] == "hive-e2e-cpu":
            run_hive_e2e_row()
        elif sys.argv[2] == "hive-restart":
            run_hive_restart_row()
        elif sys.argv[2] == "hive-failover":
            run_hive_failover_row()
        else:
            run_row(sys.argv[2])
    else:
        main()
